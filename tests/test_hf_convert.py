"""HuggingFace checkpoint interop (text/convert.py; reference analog:
PaddleNLP's torch-checkpoint conversion in from_pretrained).

These tests double as independent correctness evidence: converted
weights must reproduce `transformers`' torch forward pass numerically,
which pins our attention/rope/gelu/layernorm implementations against a
reference implementation we did not write.  No network — HF models are
constructed locally with random init."""
import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

import paddle_tpu as pt  # noqa: E402


def test_llama_matches_transformers():
    """Includes the GQA + rope-layout (half-split -> interleaved row
    permutation) conversion."""
    from paddle_tpu.text.llama import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.text.convert import convert_hf_llama
    from transformers import LlamaConfig as HFC, LlamaForCausalLM as HFM

    torch.manual_seed(0)
    hf = HFM(HFC(vocab_size=128, hidden_size=64, intermediate_size=128,
                 num_hidden_layers=2, num_attention_heads=4,
                 num_key_value_heads=2, max_position_embeddings=64,
                 rope_theta=10000.0, rms_norm_eps=1e-6,
                 attention_dropout=0.0)).eval()
    pt.seed(0)
    ours = LlamaForCausalLM(LlamaConfig(
        vocab_size=128, hidden_size=64, num_layers=2, num_heads=4,
        num_kv_heads=2, intermediate_size=128,
        max_position_embeddings=64, rms_norm_eps=1e-6,
        tensor_parallel=False))
    ours.eval()
    convert_hf_llama(ours, hf)

    ids = np.random.RandomState(0).randint(0, 128, (2, 16))
    with torch.no_grad():
        want = hf(torch.tensor(ids)).logits.numpy()
    got = np.asarray(ours(pt.to_tensor(ids))._array)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_bert_matches_transformers():
    from paddle_tpu.text.bert import (BertConfig,
                                      BertForSequenceClassification)
    from paddle_tpu.text.convert import convert_hf_bert
    from transformers import BertConfig as HFC, BertModel as HFM

    torch.manual_seed(0)
    hf = HFM(HFC(vocab_size=120, hidden_size=32, num_hidden_layers=2,
                 num_attention_heads=2, intermediate_size=64,
                 max_position_embeddings=48, hidden_dropout_prob=0.0,
                 attention_probs_dropout_prob=0.0)).eval()
    pt.seed(0)
    ours = BertForSequenceClassification(BertConfig(
        vocab_size=120, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=2, intermediate_size=64,
        max_position_embeddings=48, hidden_dropout_prob=0.0,
        attention_probs_dropout_prob=0.0), num_classes=2)
    ours.eval()
    convert_hf_bert(ours, hf)

    ids = np.random.RandomState(0).randint(0, 120, (2, 12))
    with torch.no_grad():
        ref = hf(torch.tensor(ids))
    seq, pooled = ours.bert(pt.to_tensor(ids))
    np.testing.assert_allclose(np.asarray(seq._array),
                               ref.last_hidden_state.numpy(),
                               rtol=2e-4, atol=2e-5)
    np.testing.assert_allclose(np.asarray(pooled._array),
                               ref.pooler_output.numpy(),
                               rtol=2e-4, atol=2e-5)


def test_gpt2_matches_transformers_and_greedy_decode():
    """Fused c_attn -> qkv_proj (Conv1D layout, no transpose) + tied
    head; greedy argmax chains must agree token-for-token."""
    from paddle_tpu.text import GPTConfig, GPTForCausalLM
    from paddle_tpu.text.convert import convert_hf_gpt2
    from transformers import GPT2Config as HFC, GPT2LMHeadModel as HFM

    torch.manual_seed(0)
    hf = HFM(HFC(vocab_size=130, n_embd=48, n_layer=2, n_head=4,
                 n_positions=64, resid_pdrop=0.0, embd_pdrop=0.0,
                 attn_pdrop=0.0)).eval()
    pt.seed(0)
    ours = GPTForCausalLM(GPTConfig(
        vocab_size=130, hidden_size=48, num_layers=2, num_heads=4,
        max_position_embeddings=64, hidden_dropout=0.0,
        attention_dropout=0.0, tensor_parallel=False))
    ours.eval()
    convert_hf_gpt2(ours, hf)

    ids = np.random.RandomState(0).randint(0, 130, (2, 16))
    with torch.no_grad():
        want = hf(torch.tensor(ids)).logits.numpy()
    got = np.asarray(ours(pt.to_tensor(ids))._array)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)

    # greedy continuation parity, full-context re-forward each step
    cur_ref = torch.tensor(ids[:1])
    cur_ours = ids[:1]
    for _ in range(6):
        with torch.no_grad():
            nt_ref = hf(cur_ref).logits[:, -1].argmax(-1)
        nt_ours = np.asarray(
            ours(pt.to_tensor(cur_ours))._array)[:, -1].argmax(-1)
        assert int(nt_ref[0]) == int(nt_ours[0])
        cur_ref = torch.cat([cur_ref, nt_ref[:, None]], 1)
        cur_ours = np.concatenate([cur_ours, nt_ours[:, None]], 1)


def test_ernie_matches_transformers():
    """ERNIE = BERT layout + task-type embeddings; the converter
    delegates the body to the BERT mapping."""
    from paddle_tpu.text.ernie import (ErnieConfig,
                                       ErnieForSequenceClassification)
    from paddle_tpu.text.convert import convert_hf_ernie
    from transformers import ErnieConfig as HFC, ErnieModel as HFM

    torch.manual_seed(0)
    hf = HFM(HFC(vocab_size=100, hidden_size=32, num_hidden_layers=2,
                 num_attention_heads=2, intermediate_size=64,
                 max_position_embeddings=32, type_vocab_size=2,
                 task_type_vocab_size=3, use_task_id=True,
                 hidden_dropout_prob=0.0,
                 attention_probs_dropout_prob=0.0)).eval()
    pt.seed(0)
    ours = ErnieForSequenceClassification(ErnieConfig(
        vocab_size=100, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=2, intermediate_size=64,
        max_position_embeddings=32, task_type_vocab_size=3,
        use_task_id=True, hidden_dropout_prob=0.0,
        attention_probs_dropout_prob=0.0), num_classes=2)
    ours.eval()
    convert_hf_ernie(ours, hf)

    ids = np.random.RandomState(0).randint(0, 100, (2, 10))
    tt = np.zeros((2, 10), np.int64)
    with torch.no_grad():
        ref = hf(torch.tensor(ids), task_type_ids=torch.tensor(tt))
    seq, pooled = ours.ernie(pt.to_tensor(ids))
    np.testing.assert_allclose(np.asarray(seq._array),
                               ref.last_hidden_state.numpy(),
                               rtol=2e-4, atol=2e-5)
    np.testing.assert_allclose(np.asarray(pooled._array),
                               ref.pooler_output.numpy(),
                               rtol=2e-4, atol=2e-5)


def test_convert_rejects_layer_count_mismatch():
    """A deeper checkpoint must not silently convert its prefix."""
    from paddle_tpu.text.llama import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.text.convert import convert_hf_llama
    from transformers import LlamaConfig as HFC, LlamaForCausalLM as HFM

    hf = HFM(HFC(vocab_size=64, hidden_size=32, intermediate_size=64,
                 num_hidden_layers=3, num_attention_heads=2,
                 num_key_value_heads=2, max_position_embeddings=32)).eval()
    pt.seed(0)
    shallow = LlamaForCausalLM(LlamaConfig(
        vocab_size=64, hidden_size=32, num_layers=2, num_heads=2,
        num_kv_heads=2, intermediate_size=64,
        max_position_embeddings=32, tensor_parallel=False))
    with pytest.raises(ValueError, match="layers"):
        convert_hf_llama(shallow, hf)


def test_convert_bf16_checkpoint():
    """Published checkpoints ship bf16 — numpy can't represent it, so
    the converter upcasts in torch."""
    from paddle_tpu.text.llama import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.text.convert import convert_hf_llama
    from transformers import LlamaConfig as HFC, LlamaForCausalLM as HFM

    hf = HFM(HFC(vocab_size=64, hidden_size=32, intermediate_size=64,
                 num_hidden_layers=1, num_attention_heads=2,
                 num_key_value_heads=2,
                 max_position_embeddings=32)).to(torch.bfloat16)
    pt.seed(0)
    ours = LlamaForCausalLM(LlamaConfig(
        vocab_size=64, hidden_size=32, num_layers=1, num_heads=2,
        num_kv_heads=2, intermediate_size=64,
        max_position_embeddings=32, tensor_parallel=False))
    convert_hf_llama(ours, hf)   # must not raise
    w = np.asarray(dict(ours.named_parameters())[
        "llama.embed_tokens.weight"]._array)
    assert np.isfinite(w).all() and np.abs(w).sum() > 0


def test_convert_rejects_shape_mismatch():
    from paddle_tpu.text.llama import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.text.convert import convert_hf_llama
    from transformers import LlamaConfig as HFC, LlamaForCausalLM as HFM

    hf = HFM(HFC(vocab_size=64, hidden_size=32, intermediate_size=64,
                 num_hidden_layers=1, num_attention_heads=2,
                 num_key_value_heads=2, max_position_embeddings=32)).eval()
    pt.seed(0)
    wrong = LlamaForCausalLM(LlamaConfig(
        vocab_size=64, hidden_size=48, num_layers=1, num_heads=2,
        num_kv_heads=2, intermediate_size=64,
        max_position_embeddings=32, tensor_parallel=False))
    with pytest.raises(ValueError, match="shape"):
        convert_hf_llama(wrong, hf)
