"""High-level Model API (reference: python/paddle/hapi/model.py) +
paddle.metric metrics."""
import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.nn as nn
from paddle_tpu.io import Dataset


class _XorDataset(Dataset):
    """Tiny separable problem: 2-class blobs."""

    def __init__(self, n=64, seed=0):
        rng = np.random.RandomState(seed)
        self.x = rng.randn(n, 8).astype(np.float32)
        w = np.random.RandomState(42).randn(8)  # same task across splits
        self.y = (self.x @ w > 0).astype(np.int64)

    def __getitem__(self, i):
        return self.x[i], self.y[i]

    def __len__(self):
        return len(self.x)


def _mlp():
    pt.seed(0)
    return nn.Sequential(nn.Linear(8, 32), nn.ReLU(), nn.Linear(32, 2))


def _prepared_model(lr=0.05):
    m = pt.Model(_mlp())
    m.prepare(optimizer=pt.optimizer.Adam(
        learning_rate=lr, parameters=m.parameters()),
        loss=nn.CrossEntropyLoss(),
        metrics=pt.metric.Accuracy())
    return m


def test_fit_reduces_loss_and_evaluate_accuracy():
    model = _prepared_model()
    train = _XorDataset(128, seed=1)
    test = _XorDataset(64, seed=2)
    hist = model.fit(train, epochs=6, batch_size=32, verbose=0)
    assert hist[-1]["loss"] < hist[0]["loss"] * 0.7
    res = model.evaluate(test, batch_size=32, verbose=0)
    assert set(res) >= {"loss", "acc"}
    assert res["acc"] > 0.8


def test_fit_with_eval_data_and_history():
    model = _prepared_model()
    hist = model.fit(_XorDataset(64), eval_data=_XorDataset(32, seed=3),
                     epochs=2, batch_size=16, verbose=0)
    assert len(hist) == 2
    assert "eval_acc" in hist[-1]


def test_predict_shapes_and_stack():
    model = _prepared_model()
    test = _XorDataset(40, seed=4)
    xs = [(test.x[i],) for i in range(40)]

    class _XOnly(Dataset):
        def __getitem__(self, i):
            return xs[i]

        def __len__(self):
            return len(xs)

    outs = model.predict(_XOnly(), batch_size=16, stack_outputs=True)
    assert outs[0].shape == (40, 2)


def test_train_eval_predict_batch():
    model = _prepared_model()
    x = np.random.randn(8, 8).astype(np.float32)
    y = np.random.randint(0, 2, (8,))
    l0 = model.train_batch([x], [y])
    assert isinstance(l0, float)
    logs = model.eval_batch([x], [y])
    assert "loss" in logs
    out = model.predict_batch([x])
    assert np.asarray(out).shape == (8, 2)


def test_early_stopping_and_checkpoint(tmp_path):
    model = _prepared_model(lr=0.0)  # lr=0 -> no improvement -> stops
    es = pt.callbacks.EarlyStopping(monitor="loss", mode="min", patience=1,
                                    save_best_model=False)
    hist = model.fit(_XorDataset(32), eval_data=_XorDataset(32, seed=5),
                     epochs=8, batch_size=16, verbose=0, callbacks=[es])
    assert len(hist) < 8  # stopped early

    model2 = _prepared_model()
    model2.fit(_XorDataset(32), epochs=1, batch_size=16, verbose=0,
               save_dir=str(tmp_path / "ckpt"))
    assert (tmp_path / "ckpt" / "final").exists()
    model3 = _prepared_model()
    model3.load(str(tmp_path / "ckpt" / "final"))
    p2 = model2.network.state_dict()
    p3 = model3.network.state_dict()
    for k in p2:
        np.testing.assert_allclose(p2[k].numpy(), p3[k].numpy(), rtol=1e-6)


def test_lr_scheduler_callback():
    net = _mlp()
    sched = pt.optimizer.lr.StepDecay(learning_rate=0.1, step_size=1,
                                      gamma=0.5)
    opt = pt.optimizer.SGD(learning_rate=sched, parameters=net.parameters())
    model = pt.Model(net)
    model.prepare(optimizer=opt, loss=nn.CrossEntropyLoss())
    model.fit(_XorDataset(32), epochs=2, batch_size=16, verbose=0,
              callbacks=[pt.callbacks.LRScheduler()])
    assert opt.get_lr() == pytest.approx(0.1 * 0.5 ** 2)


def test_metric_accuracy_topk():
    acc = pt.metric.Accuracy(topk=(1, 2))
    pred = np.array([[0.1, 0.7, 0.2], [0.8, 0.15, 0.05]], np.float32)
    label = np.array([1, 2])
    correct = acc.compute(pt.to_tensor(pred), pt.to_tensor(label))
    acc.update(np.asarray(correct))
    top1, top2 = acc.accumulate()
    assert top1 == pytest.approx(0.5)
    assert top2 == pytest.approx(0.5)
    assert acc.name() == ["acc_top1", "acc_top2"]


def test_metric_precision_recall():
    p, r = pt.metric.Precision(), pt.metric.Recall()
    preds = np.array([0.9, 0.8, 0.2, 0.6])
    labels = np.array([1, 0, 1, 1])
    p.update(preds, labels)
    r.update(preds, labels)
    assert p.accumulate() == pytest.approx(2 / 3)
    assert r.accumulate() == pytest.approx(2 / 3)


def test_metric_auc_perfect_and_random():
    auc = pt.metric.Auc()
    preds = np.array([0.9, 0.8, 0.1, 0.2])
    labels = np.array([1, 1, 0, 0])
    auc.update(preds, labels)
    assert auc.accumulate() == pytest.approx(1.0, abs=1e-3)
    auc.reset()
    auc.update(np.array([0.6, 0.6, 0.6, 0.6]), labels)
    assert auc.accumulate() == pytest.approx(0.5, abs=1e-2)


def test_model_summary(capsys):
    model = _prepared_model()
    info = model.summary()
    out = capsys.readouterr().out
    assert "parameters" in out
    assert info["total_params"] == 8 * 32 + 32 + 32 * 2 + 2


def test_evaluate_metrics_without_loss():
    """prepare(metrics=...) without a loss still splits off the label."""
    model = pt.Model(_mlp())
    model.prepare(metrics=pt.metric.Accuracy())
    res = model.evaluate(_XorDataset(32, seed=6), batch_size=16, verbose=0)
    assert "acc" in res and "loss" not in res


def test_load_skip_mismatch(tmp_path):
    model = _prepared_model()
    model.save(str(tmp_path / "m"))
    pt.seed(1)
    bigger = pt.Model(nn.Sequential(nn.Linear(8, 32), nn.ReLU(),
                                    nn.Linear(32, 4)))  # head differs
    bigger.prepare(optimizer=pt.optimizer.Adam(
        learning_rate=0.1, parameters=bigger.parameters()),
        loss=nn.CrossEntropyLoss())
    before = bigger.network.state_dict()["2.weight"].numpy().copy()
    bigger.load(str(tmp_path / "m"), skip_mismatch=True,
                reset_optimizer=True)
    after = bigger.network.state_dict()
    # matching first layer restored, mismatched head untouched
    np.testing.assert_allclose(
        after["0.weight"].numpy(),
        model.network.state_dict()["0.weight"].numpy(), rtol=1e-6)
    np.testing.assert_allclose(after["2.weight"].numpy(), before)


def test_predict_multi_output_stack():
    class TwoHead(nn.Layer):
        def __init__(self):
            super().__init__()
            self.a = nn.Linear(8, 2)
            self.b = nn.Linear(8, 3)

        def forward(self, x):
            return self.a(x), self.b(x)

    class _X(Dataset):
        def __getitem__(self, i):
            return (np.random.RandomState(i).randn(8).astype(np.float32),)

        def __len__(self):
            return 20

    pt.seed(0)
    model = pt.Model(TwoHead())
    model.prepare()
    outs = model.predict(_X(), batch_size=8, stack_outputs=True)
    assert len(outs) == 2
    assert outs[0].shape == (20, 2) and outs[1].shape == (20, 3)


def test_auc_negative_scores_clip_low():
    auc = pt.metric.Auc()
    auc.update(np.array([-0.5, -0.2, 0.9, 0.8]), np.array([0, 0, 1, 1]))
    assert auc.accumulate() == pytest.approx(1.0, abs=1e-3)
