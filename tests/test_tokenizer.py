"""Byte-level BPE tokenizer + real-data LM dataset (reference analog:
the GPT tokenizers the reference model zoo pairs with; VERDICT r2 weak
#8 — e2e text never touched real tokenized data)."""
import os

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.text import BPETokenizer, CharTokenizer

CORPUS = (
    "the quick brown fox jumps over the lazy dog. "
    "the dog barks; the fox runs away. pack my box with five dozen "
    "liquor jugs. how vexingly quick daft zebras jump! "
) * 20


def test_bpe_roundtrip_any_text():
    tok = BPETokenizer.train([CORPUS], vocab_size=400)
    for s in (CORPUS[:100], "Hello, WORLD!!", "unicode: héllo ☃ 你好",
              "tabs\tand\nnewlines"):
        assert tok.decode(tok.encode(s)) == s


def test_bpe_compresses():
    tok = BPETokenizer.train([CORPUS], vocab_size=500)
    ids = tok.encode("the quick brown fox")
    # merges must beat raw bytes
    assert len(ids) < len("the quick brown fox".encode())
    assert tok.vocab_size <= 500


def test_bpe_special_tokens():
    tok = BPETokenizer.train([CORPUS], vocab_size=300,
                             special_tokens=("<|endoftext|>",))
    ids = tok.encode("the dog<|endoftext|>the fox")
    eot = tok.special_tokens["<|endoftext|>"]
    assert eot in ids
    assert tok.decode(ids) == "the dog<|endoftext|>the fox"


def test_bpe_save_load(tmp_path):
    tok = BPETokenizer.train([CORPUS], vocab_size=300)
    p = str(tmp_path / "tok.json")
    tok.save(p)
    tok2 = BPETokenizer.load(p)
    s = "the lazy dog jumps"
    assert tok.encode(s) == tok2.encode(s)


def test_char_tokenizer():
    tok = CharTokenizer.train(["abc abc"])
    assert tok.decode(tok.encode("cab")) == "cab"


def test_lm_dataset_end_to_end_training(tmp_path):
    """REAL pipeline: text file -> BPE -> LMTextDataset -> DataLoader ->
    GPT train step; loss must drop on the tiny corpus."""
    from paddle_tpu.text.datasets import LMTextDataset
    from paddle_tpu.text import GPTConfig, GPTForCausalLM, gpt_loss_fn
    from paddle_tpu.io import DataLoader

    path = str(tmp_path / "corpus.txt")
    with open(path, "w") as f:
        f.write(CORPUS)
    tok = BPETokenizer.train([CORPUS], vocab_size=300)
    ds = LMTextDataset(path, tok, seq_len=32)
    assert len(ds) > 4
    x0, y0 = ds[0]
    np.testing.assert_array_equal(x0[1:], y0[:-1])  # shifted by one

    pt.seed(0)
    cfg = GPTConfig(vocab_size=tok.vocab_size, hidden_size=32,
                    num_layers=2, num_heads=4,
                    max_position_embeddings=32, hidden_dropout=0.0,
                    attention_dropout=0.0, tensor_parallel=False)
    m = GPTForCausalLM(cfg)
    opt = pt.optimizer.Adam(learning_rate=5e-3, parameters=m.parameters())
    step = pt.jit.train_step(m, gpt_loss_fn, opt)
    dl = DataLoader(ds, batch_size=4, shuffle=True, num_workers=0)
    first = last = None
    for epoch in range(3):
        for ids, labels in dl:
            loss = step(ids, labels)
            if first is None:
                first = float(loss)
            last = float(loss)
    assert last < first - 0.5, (first, last)


def test_native_bpe_matches_python():
    """io/native/bpe.cc encode == pure-Python encode, exactly."""
    from paddle_tpu.io.native import bpe_native
    if not bpe_native.available():
        pytest.skip("native toolchain unavailable")
    tok = BPETokenizer.train([CORPUS], vocab_size=400)
    assert tok._native is not None
    tok_py = BPETokenizer(tok.vocab, tok.merges, tok.special_tokens)
    tok_py._native = None
    for s in (CORPUS[:500], "Hello, WORLD!! 123", "héllo ☃ 你好",
              "tabs\tand\nnewlines", "a<|endoftext|>b"):
        assert tok.encode(s) == tok_py.encode(s), s
