"""DatasetFolder/ImageFolder (real, PIL-backed) + cpp_extension custom
ops (reference: vision/datasets/folder.py, utils/cpp_extension)."""
import os

import numpy as np
import pytest

import paddle_tpu as pt


def _make_image_tree(root):
    from PIL import Image
    for cls, n in (("cat", 3), ("dog", 2)):
        d = os.path.join(root, cls)
        os.makedirs(d)
        for i in range(n):
            arr = np.full((8, 8, 3), 40 * i, np.uint8)
            Image.fromarray(arr).save(os.path.join(d, f"{i}.png"))


def test_dataset_folder(tmp_path):
    root = str(tmp_path / "data")
    os.makedirs(root)
    _make_image_tree(root)
    from paddle_tpu.vision.datasets import DatasetFolder
    ds = DatasetFolder(root)
    assert ds.classes == ["cat", "dog"]
    assert len(ds) == 5
    img, label = ds[0]
    assert img.shape == (8, 8, 3) and label == 0
    labels = [l for _, l in (ds[i] for i in range(len(ds)))]
    assert labels == [0, 0, 0, 1, 1]


def test_dataset_folder_with_transform_and_loader(tmp_path):
    root = str(tmp_path / "data")
    os.makedirs(root)
    _make_image_tree(root)
    from paddle_tpu.vision.datasets import DatasetFolder
    from paddle_tpu.vision import transforms as T
    ds = DatasetFolder(root, transform=T.Compose(
        [T.ToTensor(), T.Normalize([0.5] * 3, [0.5] * 3)]))
    img, _ = ds[1]
    assert img.shape == [3, 8, 8]


def test_image_folder_flat(tmp_path):
    root = str(tmp_path / "imgs")
    os.makedirs(root)
    from PIL import Image
    for i in range(4):
        Image.fromarray(np.zeros((4, 4, 3), np.uint8)).save(
            os.path.join(root, f"x{i}.png"))
    from paddle_tpu.vision.datasets import ImageFolder
    ds = ImageFolder(root)
    assert len(ds) == 4
    (img,) = ds[0]
    assert img.shape == (4, 4, 3)


def test_dataset_folder_empty_raises(tmp_path):
    from paddle_tpu.vision.datasets import DatasetFolder
    with pytest.raises(ValueError, match="class folders"):
        DatasetFolder(str(tmp_path))


def test_cpp_extension_custom_op(tmp_path):
    from paddle_tpu.utils import cpp_extension
    src = str(tmp_path / "cube.cc")
    with open(src, "w") as f:
        f.write("""
extern "C" void cube_op(const float* x, float* out, long n) {
  for (long i = 0; i < n; ++i) out[i] = x[i] * x[i] * x[i];
}
""")
    lib = cpp_extension.load(name="cube", sources=[src],
                             build_directory=str(tmp_path))
    cube = cpp_extension.register_op(
        lib, "cube_op", grad_fn=lambda a, ct: 3.0 * a * a * ct)
    x = pt.to_tensor(np.array([1.0, 2.0, -3.0], np.float32))
    np.testing.assert_allclose(cube(x).numpy(), [1.0, 8.0, -27.0])
    # under jit via pure_callback
    import jax
    import jax.numpy as jnp
    from paddle_tpu.ops.dispatch import call_raw
    out = jax.jit(lambda a: call_raw("custom_cube_op", a))(
        jnp.asarray([2.0]))
    np.testing.assert_allclose(np.asarray(out), [8.0])
    # tape gradient through the C kernel
    t = pt.to_tensor(np.array([2.0], np.float32))
    t.stop_gradient = False
    cube(t).sum().backward()
    np.testing.assert_allclose(t.grad.numpy(), [12.0])
