"""Regression tests for code-review findings (round 1)."""
import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.nn.functional as F


def test_scatter_ops_run():
    x = pt.zeros([4, 3])
    idx = pt.to_tensor([0, 2])
    upd = pt.ones([2, 3])
    out = pt.scatter(x, idx, upd)
    np.testing.assert_allclose(out.numpy()[0], np.ones(3))
    np.testing.assert_allclose(out.numpy()[1], np.zeros(3))
    out2 = pt.scatter(x, idx, upd, overwrite=False)
    np.testing.assert_allclose(out2.numpy()[2], np.ones(3))

    nd_idx = pt.to_tensor([[0], [1]])
    out3 = pt.scatter_nd_add(pt.zeros([3, 2]), nd_idx, pt.ones([2, 2]))
    np.testing.assert_allclose(out3.numpy().sum(), 4.0)

    out4 = pt.index_add(pt.zeros([3, 2]), pt.to_tensor([1]), 0,
                        pt.ones([1, 2]))
    np.testing.assert_allclose(out4.numpy()[1], np.ones(2))

    x5 = pt.zeros([2, 3])
    out5 = pt.put_along_axis(x5, pt.to_tensor([[0], [2]]), 9.0, axis=1)
    assert float(out5.numpy()[0, 0]) == 9.0
    assert float(out5.numpy()[1, 2]) == 9.0


def test_cross_entropy_mean_ignores_padded():
    logits = pt.randn([4, 5])
    labels = pt.to_tensor([1, 1, -100, -100])
    full = F.cross_entropy(logits[:2], labels[:2])
    padded = F.cross_entropy(logits, labels)
    np.testing.assert_allclose(float(full), float(padded), rtol=1e-5)


def test_grad_outputs_none_entry():
    x = pt.to_tensor([3.0], stop_gradient=False)
    y = x * x
    (gx,) = pt.grad([y], [x], grad_outputs=[None])
    np.testing.assert_allclose(gx.numpy(), [6.0])


def test_nll_loss_weight():
    logp = F.log_softmax(pt.randn([4, 3]))
    labels = pt.to_tensor([0, 1, 2, 0])
    w = pt.to_tensor([10.0, 1.0, 1.0])
    weighted = F.nll_loss(logp, labels, weight=w)
    unweighted = F.nll_loss(logp, labels)
    assert abs(float(weighted) - float(unweighted)) > 1e-6


def test_pool_ceil_mode():
    x = pt.randn([1, 1, 5, 5])
    out = F.max_pool2d(x, 2, stride=2, ceil_mode=True)
    assert out.shape == [1, 1, 3, 3]
    out2 = F.max_pool2d(x, 2, stride=2, ceil_mode=False)
    assert out2.shape == [1, 1, 2, 2]
    a = F.avg_pool2d(x, 2, stride=2, ceil_mode=True)
    assert a.shape == [1, 1, 3, 3]


def test_conv2d_transpose_list_dilation():
    x = pt.randn([1, 2, 5, 5])
    w = pt.randn([2, 3, 3, 3])
    out_int = F.conv2d_transpose(x, w, dilation=1)
    out_list = F.conv2d_transpose(x, w, dilation=[1, 1])
    assert out_int.shape == out_list.shape == [1, 3, 7, 7]
    np.testing.assert_allclose(out_int.numpy(), out_list.numpy(), rtol=1e-5)


def test_interpolate_align_corners():
    x = pt.to_tensor(np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4))
    out = F.interpolate(x, size=(7, 7), mode="bilinear", align_corners=True)
    # corners must match exactly under align_corners
    assert float(out.numpy()[0, 0, 0, 0]) == 0.0
    assert float(out.numpy()[0, 0, -1, -1]) == 15.0
    out_hp = F.interpolate(x, size=(7, 7), mode="bilinear",
                           align_corners=False)
    assert not np.allclose(out.numpy(), out_hp.numpy())


def test_dropout_downscale_in_infer():
    x = pt.ones([100])
    out_infer = F.dropout(x, p=0.5, training=False,
                          mode="downscale_in_infer")
    np.testing.assert_allclose(out_infer.numpy(), np.full(100, 0.5))
    out_train = F.dropout(x, p=0.5, training=True,
                          mode="downscale_in_infer")
    kept = out_train.numpy()[out_train.numpy() != 0]
    np.testing.assert_allclose(kept, np.ones_like(kept))  # no upscale


def test_amp_o2_autocast_no_recursion():
    import paddle_tpu as pt
    x = pt.ones([4, 4], dtype="float32")
    y = pt.ones([4, 4], dtype="float32")
    with pt.amp.auto_cast(level="O2", dtype="bfloat16"):
        z = x + y
        w = z.matmul(y)
    assert str(z.dtype).endswith("bfloat16")
    assert str(w.dtype).endswith("bfloat16")


def test_grad_scaler_unscale_then_step_single_unscale():
    import paddle_tpu as pt
    p = pt.create_parameter([1], "float32",
                            default_initializer=pt.nn.initializer.Constant(1.0))
    opt = pt.optimizer.SGD(learning_rate=0.1, parameters=[p])
    scaler = pt.amp.GradScaler(init_loss_scaling=1024.0)
    loss = (p * 2.0).sum()
    scaler.scale(loss).backward()
    scaler.unscale_(opt)
    scaler.step(opt)  # must NOT unscale a second time
    # grad d(2p)/dp = 2 -> p = 1 - 0.1*2 = 0.8
    np.testing.assert_allclose(p.numpy(), [0.8], rtol=1e-5)


def test_dataloader_worker_exception_propagates():
    import pytest
    from paddle_tpu.io import DataLoader, Dataset

    class Bad(Dataset):
        def __len__(self):
            return 8

        def __getitem__(self, i):
            if i == 3:
                raise ValueError("boom")
            return np.zeros(2, np.float32)

    dl = DataLoader(Bad(), batch_size=2, num_workers=2)
    with pytest.raises(ValueError, match="boom"):
        for _ in dl:
            pass


def test_max_pool2d_return_mask():
    import paddle_tpu as pt
    from paddle_tpu.nn import functional as F
    rng = np.random.RandomState(0)
    x_np = rng.randn(2, 3, 4, 4).astype(np.float32)
    x = pt.to_tensor(x_np)
    out, mask = F.max_pool2d(x, kernel_size=2, return_mask=True)
    assert out.shape == [2, 3, 2, 2] and mask.shape == [2, 3, 2, 2]
    flat = x_np.reshape(2, 3, 16)
    gathered = np.take_along_axis(flat, mask.numpy().reshape(2, 3, 4),
                                  axis=2).reshape(2, 3, 2, 2)
    np.testing.assert_allclose(out.numpy(), gathered)


def test_hardsigmoid_slope_offset():
    import paddle_tpu as pt
    from paddle_tpu.nn import functional as F
    x = pt.to_tensor(np.array([-1.0, 0.0, 1.0], np.float32))
    out = F.hardsigmoid(x, slope=0.2, offset=0.5)
    np.testing.assert_allclose(out.numpy(), [0.3, 0.5, 0.7], rtol=1e-6)
