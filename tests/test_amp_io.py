"""AMP + IO subsystem tests (SURVEY §4)."""
import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.nn as nn


def test_autocast_o1_matmul_bf16():
    x = pt.randn([4, 4])
    y = pt.randn([4, 4])
    with pt.amp.auto_cast(level="O1", dtype="bfloat16"):
        out = x @ y
    assert out.dtype == pt.bfloat16
    # denied op stays fp32
    with pt.amp.auto_cast(level="O1", dtype="bfloat16"):
        s = nn.functional.softmax(x)
    assert s.dtype == pt.float32


def test_autocast_disabled():
    x = pt.randn([4, 4])
    with pt.amp.auto_cast(enable=False):
        out = x @ x
    assert out.dtype == pt.float32


def test_grad_scaler_scales_and_steps():
    x = pt.parameter([1.0])
    opt = pt.optimizer.SGD(learning_rate=0.1, parameters=[x])
    scaler = pt.amp.GradScaler(init_loss_scaling=4.0)
    loss = (x * 2.0).sum()
    scaled = scaler.scale(loss)
    assert float(scaled) == pytest.approx(float(loss) * 4.0)
    scaled.backward()
    scaler.step(opt)  # unscale: grad 8/4=2 → x = 1 - 0.2
    np.testing.assert_allclose(x.numpy(), [0.8], rtol=1e-5)


def test_grad_scaler_skips_on_inf():
    x = pt.parameter([1.0])
    opt = pt.optimizer.SGD(learning_rate=0.1, parameters=[x])
    scaler = pt.amp.GradScaler(init_loss_scaling=4.0)
    x.grad = pt.to_tensor([float("inf")])
    scaler.step(opt)
    scaler.update()
    np.testing.assert_allclose(x.numpy(), [1.0])  # step skipped
    assert scaler.get_loss_scaling() < 4.0  # scale shrank


def test_grad_scaler_two_optimizers_independent_inf():
    # opt1's inf verdict must survive opt2's finite unscale (per-opt found_inf)
    x1 = pt.parameter([1.0])
    x2 = pt.parameter([1.0])
    opt1 = pt.optimizer.SGD(learning_rate=0.1, parameters=[x1])
    opt2 = pt.optimizer.SGD(learning_rate=0.1, parameters=[x2])
    scaler = pt.amp.GradScaler(init_loss_scaling=4.0)
    x1.grad = pt.to_tensor([float("inf")])
    x2.grad = pt.to_tensor([4.0])
    scaler.unscale_(opt1)
    scaler.unscale_(opt2)
    scaler.step(opt1)
    scaler.step(opt2)
    scaler.update()
    np.testing.assert_allclose(x1.numpy(), [1.0])  # inf → skipped
    np.testing.assert_allclose(x2.numpy(), [0.9], rtol=1e-5)  # 1 - 0.1*1
    # the iteration saw an inf, so the per-iteration update must shrink
    assert scaler.get_loss_scaling() < 4.0


def test_amp_decorate_o2():
    m = nn.Linear(4, 4)
    m, _ = pt.amp.decorate(models=m, optimizers=pt.optimizer.SGD(
        learning_rate=0.1, parameters=m.parameters()), dtype="bfloat16")
    assert m.weight.dtype == pt.bfloat16


def test_dataset_dataloader():
    from paddle_tpu.io import TensorDataset, DataLoader
    X = pt.randn([20, 4]); Y = pt.arange(20)
    ds = TensorDataset([X, Y])
    assert len(ds) == 20
    dl = DataLoader(ds, batch_size=6, drop_last=False)
    batches = list(dl)
    assert len(batches) == 4
    assert batches[0][0].shape == [6, 4]
    assert batches[-1][0].shape == [2, 4]
    dl2 = DataLoader(ds, batch_size=5, shuffle=True, drop_last=True,
                     num_workers=2)
    batches = list(dl2)
    assert len(batches) == 4


def test_random_split_subset():
    from paddle_tpu.io import TensorDataset, random_split
    ds = TensorDataset([pt.arange(10)])
    a, b = random_split(ds, [7, 3])
    assert len(a) == 7 and len(b) == 3


def test_distributed_batch_sampler():
    from paddle_tpu.io import DistributedBatchSampler, TensorDataset
    ds = TensorDataset([pt.arange(16)])
    s0 = DistributedBatchSampler(ds, batch_size=2, num_replicas=2, rank=0)
    s1 = DistributedBatchSampler(ds, batch_size=2, num_replicas=2, rank=1)
    i0 = [i for b in s0 for i in b]
    i1 = [i for b in s1 for i in b]
    assert len(i0) == 8 and len(i1) == 8
    assert not set(i0) & set(i1)


def test_fake_data():
    ds = pt.vision.datasets.FakeData(size=10, image_shape=(3, 8, 8),
                                     num_classes=4)
    img, label = ds[0]
    assert img.shape == (3, 8, 8)
    assert 0 <= int(label) < 4
    img2, label2 = ds[0]
    np.testing.assert_allclose(img, img2)  # deterministic per index


def test_transforms():
    from paddle_tpu.vision import transforms as T
    img = (np.random.rand(32, 32, 3) * 255).astype(np.uint8)
    t = T.Compose([T.Resize(16), T.CenterCrop(8), T.ToTensor()])
    out = t(img)
    assert out.shape == [3, 8, 8]
    n = T.Normalize(mean=[0.5] * 3, std=[0.5] * 3)
    assert n(out).shape == [3, 8, 8]


def test_profiler_timer():
    p = pt.profiler.Profiler(timer_only=True)
    p.start()
    for _ in range(3):
        p.step()
    p.stop()
    assert "steps=3" in p.summary()


def test_check_numerics_flag():
    from paddle_tpu.framework import flags
    flags.set_flags({"check_numerics": True})
    assert flags.get_flags("check_numerics")
    flags.set_flags({"check_numerics": False})
