"""LBFGS (reference: python/paddle/optimizer/lbfgs.py) — closure API,
strong-Wolfe line search, classic convergence checks."""
import numpy as np
import pytest

import paddle_tpu as pt


def test_lbfgs_rosenbrock_strong_wolfe():
    w = pt.to_tensor(np.array([-1.2, 1.0], np.float32))
    w.stop_gradient = False
    opt = pt.optimizer.LBFGS(learning_rate=1.0, max_iter=30,
                             line_search_fn="strong_wolfe",
                             parameters=[w])

    def closure():
        opt.clear_grad()
        x, y = w[0], w[1]
        loss = (1.0 - x) ** 2 + 100.0 * (y - x ** 2) ** 2
        loss.backward()
        return loss

    for _ in range(10):
        loss = opt.step(closure)
    assert float(loss) < 1e-5
    np.testing.assert_allclose(w.numpy(), [1.0, 1.0], atol=1e-2)


def test_lbfgs_quadratic_no_line_search():
    v = pt.to_tensor(np.array([3.0, -4.0, 5.0], np.float32))
    v.stop_gradient = False
    opt = pt.optimizer.LBFGS(learning_rate=0.5, max_iter=10,
                             parameters=[v])

    def closure():
        opt.clear_grad()
        loss = (v ** 2).sum()
        loss.backward()
        return loss

    for _ in range(5):
        loss = opt.step(closure)
    assert float(loss) < 1e-6


def test_lbfgs_state_dict_round_trip():
    w = pt.to_tensor(np.array([-1.2, 1.0], np.float32))
    w.stop_gradient = False
    opt = pt.optimizer.LBFGS(learning_rate=1.0, max_iter=5,
                             line_search_fn="strong_wolfe",
                             parameters=[w])

    def closure():
        opt.clear_grad()
        x, y = w[0], w[1]
        loss = (1.0 - x) ** 2 + 100.0 * (y - x ** 2) ** 2
        loss.backward()
        return loss

    opt.step(closure)
    sd = opt.state_dict()
    assert any(k.startswith("__lbfgs__/s") for k in sd)
    w2 = pt.to_tensor(np.array([-1.2, 1.0], np.float32))
    w2.stop_gradient = False
    opt2 = pt.optimizer.LBFGS(learning_rate=1.0, max_iter=5,
                              line_search_fn="strong_wolfe",
                              parameters=[w2])
    opt2.set_state_dict(sd)
    assert len(opt2._state_lb["s"]) == len(opt._state_lb["s"]) > 0


def test_lbfgs_weight_decay_active():
    v = pt.to_tensor(np.array([2.0], np.float32))
    v.stop_gradient = False
    opt = pt.optimizer.LBFGS(learning_rate=0.1, max_iter=3,
                             weight_decay=1.0, parameters=[v])

    def closure():
        opt.clear_grad()
        loss = ((v - 2.0) ** 2).sum()
        loss.backward()
        return loss

    for _ in range(20):
        opt.step(closure)
    # L2 decay pulls the optimum below the data term's v=2
    assert float(v.numpy()[0]) < 1.9


def test_lbfgs_requires_closure():
    v = pt.to_tensor(np.array([1.0], np.float32))
    v.stop_gradient = False
    opt = pt.optimizer.LBFGS(parameters=[v])
    with pytest.raises(ValueError, match="closure"):
        opt.step()
