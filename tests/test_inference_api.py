"""Paddle Inference deployment API over the StableHLO artifacts."""
import os

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu.framework import static_graph as SG


def test_predictor_over_static_export(tmp_path):
    paddle.enable_static()
    SG.reset()
    try:
        main = paddle.static.Program()
        with paddle.static.program_guard(main):
            x = paddle.static.data("x", [None, 4], "float32")
            model = nn.Linear(4, 3)
            pred = F.softmax(model(x))
        exe = paddle.static.Executor()
        feed = {"x": np.ones((2, 4), np.float32)}
        (want,) = exe.run(main, feed=feed, fetch_list=[pred])
        path = os.path.join(str(tmp_path), "deploy")
        with paddle.static.program_guard(main):
            paddle.static.save_inference_model(path, [x], [pred], exe)
    finally:
        SG.reset()
        paddle.disable_static()

    config = paddle.inference.Config(path)
    config.enable_memory_optim()
    predictor = paddle.inference.create_predictor(config)
    assert predictor.get_input_names() == ["x"]
    h = predictor.get_input_handle("x")
    h.copy_from_cpu(np.ones((2, 4), np.float32))
    assert predictor.run()
    out = predictor.get_output_handle(
        predictor.get_output_names()[0]).copy_to_cpu()
    np.testing.assert_allclose(out, want, rtol=1e-5)


def test_predictor_over_jit_save(tmp_path):
    paddle.seed(0)
    model = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    model.eval()
    x = paddle.randn([2, 4])
    want = model(x).numpy()
    path = os.path.join(str(tmp_path), "jitdeploy")
    from paddle_tpu.jit.save_load import InputSpec, save_inference
    save_inference(model, path, [InputSpec([None, 4], "float32", "x")])

    predictor = paddle.inference.create_predictor(
        paddle.inference.Config(path))
    # canonical recipe: output names/handles are valid BEFORE run()
    out_names = predictor.get_output_names()
    assert out_names == ["output_0"]
    pre_handle = predictor.get_output_handle(out_names[0])
    h = predictor.get_input_handle(predictor.get_input_names()[0])
    h.copy_from_cpu(x.numpy())
    predictor.run()
    np.testing.assert_allclose(pre_handle.copy_to_cpu(), want,
                               rtol=1e-5, atol=1e-6)


def test_predictor_missing_feed_raises(tmp_path):
    paddle.seed(0)
    model = nn.Linear(2, 2)
    model.eval()
    path = os.path.join(str(tmp_path), "m")
    from paddle_tpu.jit.save_load import InputSpec, save_inference
    save_inference(model, path, [InputSpec([None, 2], "float32", "x")])
    predictor = paddle.inference.create_predictor(
        paddle.inference.Config(path))
    with pytest.raises(ValueError, match="not fed"):
        predictor.run()


def test_text_datasets_surface():
    from paddle_tpu.text import datasets as D
    ds = D.FakeTextDataset(num_samples=10, seq_len=8)
    ids, label = ds[0]
    assert ids.shape == (8,) and len(ds) == 10
    with pytest.raises(NotImplementedError, match="offline"):
        D.Imdb()
