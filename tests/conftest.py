"""Test env: CPU backend with 8 virtual devices (multi-chip sharding tests
run on a virtual mesh, per the driver's dryrun contract).

NOTE: the axon TPU plugin force-sets jax.config.jax_platforms at import time,
so the env var alone is not enough — we must override through jax.config
before any backend is touched.
"""
import os

flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _seed():
    import paddle_tpu
    paddle_tpu.seed(42)
    yield
