"""Test env: CPU backend with 8 virtual devices (multi-chip sharding tests
run on a virtual mesh, per the driver's dryrun contract).

NOTE: the axon TPU plugin force-sets jax.config.jax_platforms at import time,
so the env var alone is not enough — we must override through jax.config
before any backend is touched.
"""
import os

flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: deselected from the tier-1 run (-m 'not slow')")
    config.addinivalue_line(
        "markers", "needs_partial_manual: requires jax with native "
        "partial-manual shard_map (axis_names=); skipped on old jax")


def pytest_collection_modifyitems(config, items):
    from paddle_tpu.framework.compat import HAS_PARTIAL_MANUAL
    if HAS_PARTIAL_MANUAL:
        return
    skip = pytest.mark.skip(
        reason="partial-manual shard_map (GSPMD dp/mp inside a pp-manual "
               "region) needs jax with native axis_names= support; this "
               "jax's auto= lowering hits a fatal XLA CHECK "
               "(framework/compat.py)")
    for item in items:
        if "needs_partial_manual" in item.keywords:
            item.add_marker(skip)


@pytest.fixture(autouse=True)
def _seed():
    import paddle_tpu
    paddle_tpu.seed(42)
    yield
