"""Pallas flash attention vs the XLA sdpa reference, interpret mode on CPU.

Mirrors the reference's flash-attn unit tests
(test/legacy_test/test_flash_attention.py): forward allclose vs the
naive softmax path, gradients allclose via vjp, causal and full.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.ops.pallas import flash_attention as fa
from paddle_tpu.ops import call_raw
from paddle_tpu.ops.nn_kernels import sdpa_k


def _rand_qkv(rng, B, L, H, D, dtype=jnp.float32):
    q = jnp.asarray(rng.standard_normal((B, L, H, D)), dtype)
    k = jnp.asarray(rng.standard_normal((B, L, H, D)), dtype)
    v = jnp.asarray(rng.standard_normal((B, L, H, D)), dtype)
    return q, k, v


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("shape", [(2, 128, 2, 64), (1, 256, 4, 32)])
def test_flash_forward_matches_sdpa(causal, shape):
    rng = np.random.default_rng(0)
    q, k, v = _rand_qkv(rng, *shape)
    out = fa.flash_attention(q, k, v, is_causal=causal, interpret=True)
    ref = sdpa_k(q, k, v, is_causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_grads_match_sdpa(causal):
    rng = np.random.default_rng(1)
    q, k, v = _rand_qkv(rng, 1, 128, 2, 64)

    def loss_flash(q, k, v):
        o = fa.flash_attention(q, k, v, is_causal=causal, interpret=True)
        return jnp.sum(jnp.sin(o))

    def loss_ref(q, k, v):
        return jnp.sum(jnp.sin(sdpa_k(q, k, v, is_causal=causal)))

    g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4)


def test_flash_under_jit():
    rng = np.random.default_rng(2)
    q, k, v = _rand_qkv(rng, 1, 128, 2, 64)
    f = jax.jit(lambda q, k, v: fa.flash_attention(
        q, k, v, is_causal=True, interpret=True))
    out = f(q, k, v)
    ref = sdpa_k(q, k, v, is_causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_registry_override_falls_back_on_cpu():
    # without PADDLE_TPU_PALLAS=interpret the CPU backend must use XLA sdpa
    rng = np.random.default_rng(3)
    q, k, v = _rand_qkv(rng, 1, 64, 2, 16)
    out = call_raw("sdpa", q, k, v, None, is_causal=True)
    ref = sdpa_k(q, k, v, is_causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-6)


def test_registry_override_interpret(monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_PALLAS", "interpret")
    rng = np.random.default_rng(4)
    q, k, v = _rand_qkv(rng, 2, 128, 2, 64)
    out = call_raw("sdpa", q, k, v, None, is_causal=True)
    ref = sdpa_k(q, k, v, is_causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_flash_causal_cross_length():
    # bottom-right-aligned causal (KV-cache prefill: Lk > Lq) must match the
    # XLA path's jnp.tril(..., lk - lq) alignment
    rng = np.random.default_rng(5)
    B, H, D = 1, 2, 64
    q = jnp.asarray(rng.standard_normal((B, 64, H, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, 128, H, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, 128, H, D)), jnp.float32)
    out = fa.flash_attention(q, k, v, is_causal=True, interpret=True)
    ref = sdpa_k(q, k, v, is_causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_supports_gate():
    s = (2, 128, 4, 64)
    assert fa.supports(s, s, None, jnp.float32)
    assert not fa.supports(s, s, object(), jnp.float32)   # weird mask obj
    # ragged (round 3): handled by internal padding now
    assert fa.supports((2, 100, 4, 64), s, None, jnp.float32)
    assert not fa.supports(s, s, None, jnp.int32)


# ----------------------------------------------------- round-3 extensions
def _ref_gqa(q, k, v, mask=None, is_causal=False):
    return sdpa_k(q, k, v, mask=mask, is_causal=is_causal)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_gqa_matches_repeat(causal):
    # kv heads grouped inside the kernel == repeat_interleave + dense
    rng = np.random.default_rng(6)
    B, L, H, Hkv, D = 2, 128, 8, 2, 64
    q = jnp.asarray(rng.standard_normal((B, L, H, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, L, Hkv, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, L, Hkv, D)), jnp.float32)
    out = fa.flash_attention(q, k, v, is_causal=causal, interpret=True)
    kr = jnp.repeat(k, H // Hkv, axis=2)
    vr = jnp.repeat(v, H // Hkv, axis=2)
    ref = sdpa_k(q, kr, vr, is_causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_flash_gqa_grads():
    rng = np.random.default_rng(7)
    B, L, H, Hkv, D = 1, 128, 4, 2, 32
    q = jnp.asarray(rng.standard_normal((B, L, H, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, L, Hkv, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, L, Hkv, D)), jnp.float32)

    def loss_flash(q, k, v):
        o = fa.flash_attention(q, k, v, is_causal=True, interpret=True)
        return jnp.sum(jnp.sin(o))

    def loss_ref(q, k, v):
        kr = jnp.repeat(k, H // Hkv, axis=2)
        vr = jnp.repeat(v, H // Hkv, axis=2)
        return jnp.sum(jnp.sin(sdpa_k(q, kr, vr, is_causal=True)))

    g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("mask_kind", ["bool_padding", "additive_full",
                                       "bool_full_bh"])
def test_flash_masks(mask_kind):
    rng = np.random.default_rng(8)
    B, L, H, D = 2, 128, 2, 64
    q, k, v = _rand_qkv(rng, B, L, H, D)
    if mask_kind == "bool_padding":
        # (B, 1, 1, Lk) key-padding mask, rows broadcast
        lens = np.array([100, 77])
        m = (np.arange(L)[None, :] < lens[:, None])
        mask = jnp.asarray(m)[:, None, None, :]
    elif mask_kind == "additive_full":
        mask = jnp.asarray(
            np.where(rng.random((B, 1, L, L)) < 0.8, 0.0, -1e9), jnp.float32)
    else:
        mask = jnp.asarray(rng.random((B, H, L, L)) < 0.9)
    assert fa.supports(q.shape, k.shape, mask, q.dtype)
    out = fa.flash_attention(q, k, v, mask=mask, interpret=True)
    ref = sdpa_k(q, k, v, mask=mask)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_flash_mask_grads():
    rng = np.random.default_rng(9)
    B, L, H, D = 1, 128, 2, 32
    q, k, v = _rand_qkv(rng, B, L, H, D)
    lens = np.array([90])
    mask = jnp.asarray((np.arange(L)[None, :] < lens[:, None]))[:, None,
                                                                None, :]

    def loss_flash(q, k, v):
        o = fa.flash_attention(q, k, v, mask=mask, interpret=True)
        return jnp.sum(jnp.sin(o))

    def loss_ref(q, k, v):
        return jnp.sum(jnp.sin(sdpa_k(q, k, v, mask=mask)))

    g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("shape", [(1, 100, 2, 64), (2, 257, 2, 32),
                                   (1, 7, 2, 64)])
def test_flash_ragged_lens(shape):
    # non-block-divisible seq lens: padded internally, cols masked
    rng = np.random.default_rng(10)
    q, k, v = _rand_qkv(rng, *shape)
    for causal in (False, True):
        out = fa.flash_attention(q, k, v, is_causal=causal, interpret=True)
        ref = sdpa_k(q, k, v, is_causal=causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)


def test_flash_ragged_grads():
    rng = np.random.default_rng(11)
    q, k, v = _rand_qkv(rng, 1, 100, 2, 32)

    def loss_flash(q, k, v):
        o = fa.flash_attention(q, k, v, is_causal=True, interpret=True)
        return jnp.sum(jnp.sin(o))

    def loss_ref(q, k, v):
        return jnp.sum(jnp.sin(sdpa_k(q, k, v, is_causal=True)))

    g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4)


def test_flash_decode_shape():
    # Lq=1 single-token decode against a KV cache with a padding mask
    rng = np.random.default_rng(12)
    B, Lk, H, D = 2, 128, 4, 64
    q = jnp.asarray(rng.standard_normal((B, 1, H, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, Lk, H, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, Lk, H, D)), jnp.float32)
    lens = np.array([64, 100])
    mask = jnp.asarray((np.arange(Lk)[None, :] < lens[:, None]))[:, None,
                                                                 None, :]
    out = fa.flash_attention(q, k, v, mask=mask, interpret=True)
    ref = sdpa_k(q, k, v, mask=mask)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_supports_gate_round3():
    s = (2, 128, 4, 64)
    skv = (2, 128, 2, 64)   # GQA now supported
    assert fa.supports(s, skv, None, jnp.float32)
    assert not fa.supports(s, (2, 128, 3, 64), None, jnp.float32)  # 4%3
    assert fa.supports((2, 100, 4, 64), s[:1] + (100,) + s[2:], None,
                       jnp.float32)  # ragged now supported
    mask = jnp.zeros((2, 1, 128, 128), jnp.float32)
    assert fa.supports(s, s, mask, jnp.float32)
    assert not fa.supports(s, s, object(), jnp.float32)  # weird mask obj
    assert not fa.supports(s, s, None, jnp.int32)
