"""Round-3 pipeline-parallel extensions (reference: fleet meta_parallel
pipeline_parallel.py): pp x MoE (router aux escapes the pipelined scan),
read-only buffers inside pipelined blocks, and compiled peak-memory
evidence for the remat'd GPipe schedule."""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.distributed import fleet
from paddle_tpu.distributed import mesh as mesh_mod


@pytest.fixture
def restore_mesh():
    prev = dict(mesh_mod._state)
    yield
    mesh_mod._state.update(prev)


def _moe_gpt(seed=13, layers=4):
    from paddle_tpu.text import GPTConfig, GPTForCausalLM
    pt.seed(seed)
    cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=layers,
                    num_heads=4, max_position_embeddings=32,
                    hidden_dropout=0.0, attention_dropout=0.0,
                    tensor_parallel=False, num_experts=4,
                    moe_capacity_factor=4.0)   # no token dropping
    return GPTForCausalLM(cfg)


def test_fleet_pp_moe_matches_microbatched_serial(restore_mesh):
    """pp=2 x MoE: CE over the full batch + aux averaged over microbatches
    must equal the same computation done serially per microbatch (gating
    statistics are per-microbatch under pp — the reference's semantics)."""
    from paddle_tpu.text import gpt_loss_fn
    from paddle_tpu.incubate.nn import moe_aux_loss
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 1, "mp_degree": 1,
                               "pp_degree": 2, "accumulate_steps": 2}
    fleet.init(is_collective=True, strategy=strategy)

    m_pp = _moe_gpt()
    m_ref = _moe_gpt(seed=99)
    m_ref.set_state_dict(m_pp.state_dict())

    o_pp = pt.optimizer.Adam(learning_rate=0.02,
                             parameters=m_pp.parameters())
    step = fleet.build_train_step(m_pp, gpt_loss_fn, o_pp)
    o_ref = pt.optimizer.Adam(learning_rate=0.02,
                              parameters=m_ref.parameters())

    pt.seed(7)
    M = 2
    ids = pt.randint(0, 64, [4, 16])
    labels = pt.randint(0, 64, [4, 16])
    import paddle_tpu.nn.functional as F
    w = m_ref.cfg.moe_aux_weight

    for _ in range(2):
        pp_loss = step(ids, labels)
        # reference: full-batch CE + microbatch-averaged router aux
        logits_parts, auxes = [], []
        for mb in range(M):
            sl = slice(mb * 2, (mb + 1) * 2)
            logits_parts.append(m_ref(ids[sl]))
            auxes.append(moe_aux_loss(m_ref))
        logits = pt.concat(logits_parts, axis=0)
        ce = F.cross_entropy(logits, labels, reduction="mean")
        aux = sum(auxes[1:], auxes[0]) / float(M)
        ref_loss = ce + w * aux
        ref_loss.backward()
        o_ref.step(); o_ref.clear_grad()
        np.testing.assert_allclose(float(pp_loss), float(ref_loss),
                                   rtol=3e-4)
    step.sync_model()
    ref_params = dict(m_ref.named_parameters())
    for n, p in m_pp.named_parameters():
        np.testing.assert_allclose(p.numpy(), ref_params[n].numpy(),
                                   rtol=2e-3, atol=5e-4,
                                   err_msg=n)


class _ScaledBlock(pt.nn.Layer):
    """Homogeneous block holding a READ-ONLY buffer used in forward."""

    def __init__(self, d, scale):
        super().__init__()
        self.fc = pt.nn.Linear(d, d)
        self.register_buffer("scale", pt.to_tensor(np.float32(scale)))

    def forward(self, x):
        import paddle_tpu.nn.functional as F
        return x + F.gelu(self.fc(x)) * self.scale


class _BufferedNet(pt.nn.Layer):
    def __init__(self, d=16, n=4):
        super().__init__()
        self.inp = pt.nn.Linear(d, d)
        self.blocks = pt.nn.LayerList(
            [_ScaledBlock(d, 0.5 + 0.25 * i) for i in range(n)])
        self.head = pt.nn.Linear(d, d)

    def forward(self, x):
        h = self.inp(x)
        for b in self.blocks:
            h = b(h)
        return self.head(h)

    def pipeline_decompose(self):
        return {"blocks": list(self.blocks),
                "pre": lambda x: self.inp(x),
                "post": lambda h: self.head(h)}


def _mse_loss(model, x, y):
    out = model(x)
    return ((out - y) ** 2).mean()


def test_pp_blocks_with_readonly_buffers(restore_mesh):
    """Round-2 restriction lifted: per-block buffers ride the pipelined
    scan read-only; pp training == serial eager training."""
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 1, "mp_degree": 1,
                               "pp_degree": 2, "accumulate_steps": 2}
    fleet.init(is_collective=True, strategy=strategy)

    pt.seed(5)
    m_pp = _BufferedNet()
    pt.seed(6)
    m_ref = _BufferedNet()
    m_ref.set_state_dict(m_pp.state_dict())

    o_pp = pt.optimizer.SGD(learning_rate=0.1,
                            parameters=m_pp.parameters())
    step = fleet.build_train_step(m_pp, _mse_loss, o_pp)
    o_ref = pt.optimizer.SGD(learning_rate=0.1,
                             parameters=m_ref.parameters())

    rng = np.random.default_rng(0)
    x = pt.to_tensor(rng.standard_normal((4, 16)).astype(np.float32))
    y = pt.to_tensor(rng.standard_normal((4, 16)).astype(np.float32))
    for _ in range(3):
        pp_loss = step(x, y)
        ref_loss = _mse_loss(m_ref, x, y)
        ref_loss.backward()
        o_ref.step(); o_ref.clear_grad()
        np.testing.assert_allclose(float(pp_loss), float(ref_loss),
                                   rtol=2e-5)
    step.sync_model()
    ref_params = dict(m_ref.named_parameters())
    for n, p in m_pp.named_parameters():
        np.testing.assert_allclose(p.numpy(), ref_params[n].numpy(),
                                   rtol=1e-4, atol=1e-5, err_msg=n)


class _BNBlock(pt.nn.Layer):
    def __init__(self, d):
        super().__init__()
        self.fc = pt.nn.Linear(d, d)
        self.bn = pt.nn.BatchNorm1D(d)

    def forward(self, x):
        return self.bn(self.fc(x))


class _BNNet(pt.nn.Layer):
    def __init__(self, d=8, n=2):
        super().__init__()
        self.blocks = pt.nn.LayerList([_BNBlock(d) for _ in range(n)])

    def forward(self, x):
        for b in self.blocks:
            x = b(x)
        return x

    def pipeline_decompose(self):
        return {"blocks": list(self.blocks),
                "pre": lambda x: x,
                "post": lambda h: h}


def test_pp_block_buffer_mutation_supported_vpp1(restore_mesh):
    """Round 4 (VERDICT r3 item 7): train-mode BatchNorm inside a
    pipelined block WORKS for vpp=1 — running stats ride the schedule
    scan and land back on the model (serial-parity pinned in
    tests/test_pp_buffers.py).  vpp>1 still fails loudly (see
    test_pp_buffers.test_interleaved_pp_still_rejects_bn_mutation)."""
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 1, "mp_degree": 1,
                               "pp_degree": 2, "accumulate_steps": 2}
    fleet.init(is_collective=True, strategy=strategy)

    pt.seed(1)
    m = _BNNet()
    before = {n: np.asarray(b._array).copy()
              for n, b in m.named_buffers() if "_mean" in n}
    opt = pt.optimizer.SGD(learning_rate=0.1, parameters=m.parameters())
    step = fleet.build_train_step(m, _mse_loss, opt)
    x = pt.to_tensor(np.ones((4, 8), np.float32))
    step(x, x)
    after = {n: np.asarray(b._array)
             for n, b in m.named_buffers() if "_mean" in n}
    changed = any(not np.allclose(before[n], after[n]) for n in before)
    assert changed, "BN running stats did not update under pp"


def test_pp_memory_stats_remat_lever(restore_mesh):
    """Compiled peak-memory evidence: the remat'd GPipe scan compiles to a
    significantly smaller temp footprint than the non-remat one (the lever
    that substitutes for a hand-written 1F1B schedule); both are
    measurable via the engine's AOT memory_stats()."""
    from paddle_tpu.text import GPTConfig, GPTForCausalLM, gpt_loss_fn

    stats = {}
    for remat in (False, True):
        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs = {"dp_degree": 1, "mp_degree": 1,
                                   "pp_degree": 2, "accumulate_steps": 4}
        fleet.init(is_collective=True, strategy=strategy)
        pt.seed(3)
        cfg = GPTConfig(vocab_size=128, hidden_size=64, num_layers=4,
                        num_heads=4, max_position_embeddings=64,
                        hidden_dropout=0.0, attention_dropout=0.0,
                        use_recompute=remat, tensor_parallel=False)
        m = GPTForCausalLM(cfg)
        opt = pt.optimizer.SGD(learning_rate=0.01,
                               parameters=m.parameters())
        step = fleet.build_train_step(m, gpt_loss_fn, opt)
        ids = pt.randint(0, 128, [8, 64])
        ms = step.memory_stats(ids, ids)
        assert ms.temp_size_in_bytes > 0
        stats[remat] = ms.temp_size_in_bytes

    # remat must cut the scan's held activations (bb-for-memory trade);
    # the margin is the point, not the exact ratio
    assert stats[True] < stats[False] * 0.75, stats
