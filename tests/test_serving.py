"""Continuous-batching serving engine (paddle_tpu/serving).

The load-bearing property: engine output under CONCURRENT interleaved
requests is token-identical to sequential `generate()` per request —
paged attention over gathered pool blocks runs the exact dense-cache
sdpa math, so batching/chunking/preemption may never change a token.
Plus: block-pool alloc/free/refcount invariants, preemption-and-resume
mid-decode, pallas-vs-fallback paged attention equivalence, AOT
round-trip, and the chaos overload drill (tier-1 wiring of
``chaos_check --serving``).
"""
import io
import os
import sys

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.serving import (BlockPool, LLMEngine, PoolExhausted,
                                export_serving_artifacts,
                                load_serving_artifacts)
from paddle_tpu.text import (GPTConfig, GPTForCausalLM, LlamaConfig,
                             LlamaForCausalLM)
from paddle_tpu.text.generation import generate

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _tiny_gpt():
    pt.seed(0)
    cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=2,
                    num_heads=4, max_position_embeddings=64,
                    hidden_dropout=0.0, attention_dropout=0.0,
                    tensor_parallel=False)
    return GPTForCausalLM(cfg)


@pytest.fixture(scope="module")
def gpt():
    return _tiny_gpt()


@pytest.fixture(scope="module")
def gpt_engine(gpt):
    """One shared engine (its compiled programs amortize across tests;
    every test drains its requests, so state resets between them)."""
    return LLMEngine(gpt, num_blocks=48, block_size=8, max_running=9,
                     prefill_chunk=16)


def _tiny_llama():
    pt.seed(0)
    cfg = LlamaConfig(vocab_size=64, hidden_size=32, num_layers=2,
                      num_heads=4, num_kv_heads=2, intermediate_size=64,
                      max_position_embeddings=64, tensor_parallel=False)
    return LlamaForCausalLM(cfg)


def _seq_ref(model, prompt, n, eos=None):
    out = generate(model, pt.to_tensor(np.asarray([prompt], "int64")),
                   max_new_tokens=n, eos_token_id=eos)
    return out.numpy()[0, len(prompt):].tolist()


# ===================================================================
# token parity under concurrent interleaved load (the acceptance bar:
# >= 8 concurrent requests of mixed prompt lengths)
# ===================================================================
def test_engine_parity_concurrent_interleaved(gpt, gpt_engine):
    m, eng = gpt, gpt_engine
    rng = np.random.RandomState(0)
    lens = (5, 11, 3, 9, 14, 7, 4, 12, 6)
    prompts = [rng.randint(0, 64, size=n).tolist() for n in lens]
    refs = [_seq_ref(m, p, 7) for p in prompts]

    # interleave arrivals with decoding: the first wave is mid-flight
    # when the rest join the batch
    reqs = [eng.add_request(p, max_new_tokens=7) for p in prompts[:5]]
    for _ in range(3):
        eng.step()
    reqs += [eng.add_request(p, max_new_tokens=7) for p in prompts[5:]]
    eng.run()
    outs = [list(r.generated) for r in reqs]
    assert outs == refs
    leaked, bad = eng.pool.check_leaks()
    assert not leaked and not bad
    assert eng.pool.free_blocks == eng.pool.num_blocks


def test_engine_parity_llama_gqa():
    m = _tiny_llama()
    rng = np.random.RandomState(1)
    prompts = [rng.randint(0, 64, size=n).tolist() for n in (6, 10, 4)]
    refs = [_seq_ref(m, p, 5) for p in prompts]
    eng = LLMEngine(m, num_blocks=24, block_size=8, max_running=4)
    assert eng.generate_batch(prompts, max_new_tokens=5) == refs


def test_engine_eos_stops_request(gpt, gpt_engine):
    prompt = [1, 2, 3, 4, 5]
    first = _seq_ref(gpt, prompt, 1)[0]
    ref = _seq_ref(gpt, prompt, 6, eos=first)
    [out] = gpt_engine.generate_batch([prompt], max_new_tokens=6,
                                      eos_token_id=first)
    assert out == ref
    assert gpt_engine._finished[-1].finish_reason == "eos"
    assert len(out) < 6


def test_streaming_callbacks_order(gpt_engine):
    got, done = [], []
    req = gpt_engine.add_request([3, 1, 4, 1, 5], max_new_tokens=5,
                                 on_token=lambda r, t: got.append(t),
                                 on_finish=lambda r: done.append(r.id))
    gpt_engine.run()
    assert got == list(req.generated) and len(got) == 5
    assert done == [req.id]


def test_sampled_requests_deterministic_per_seed(gpt_engine):
    prompts = [[5, 6, 7], [9, 8, 7, 6]]
    kw = dict(max_new_tokens=6, do_sample=True, temperature=0.9,
              top_k=20, seed=123)
    a = gpt_engine.generate_batch(prompts, **kw)
    b = gpt_engine.generate_batch(list(reversed(prompts)), **kw)
    # per-request numpy stream: independent of batch order/composition
    assert a == list(reversed(b))


# ===================================================================
# block pool invariants
# ===================================================================
def test_block_pool_alloc_free_refcount():
    pool = BlockPool(num_layers=1, num_blocks=8, block_size=4,
                     num_kv_heads=2, head_dim=8)
    a = pool.allocate(3)
    assert len(a) == 3 and pool.free_blocks == 5
    pool.ref(a)                       # rc 2
    pool.free(a)                      # rc 1 — still held
    assert pool.free_blocks == 5
    pool.free(a)                      # rc 0 — home
    assert pool.free_blocks == 8
    with pytest.raises(ValueError):
        pool.free(a)                  # double free
    b = pool.allocate(8)
    assert pool.allocate(1) is None   # exhausted -> None, not a raise
    with pytest.raises(PoolExhausted):
        pool.allocate(9)              # can never fit -> hard error
    pool.free(b)
    assert pool.check_leaks() == ([], [])
    with pytest.raises(ValueError):
        pool.ref([0])                 # ref of an unallocated block


def test_block_pool_blocks_for():
    pool = BlockPool(1, 8, 16, 2, 8)
    assert [pool.blocks_for(n) for n in (1, 16, 17, 32)] == [1, 1, 2, 2]


@pytest.mark.parametrize("seed", [0, 1, 2, 3, 4, 5, 6, 7])
def test_block_pool_random_interleavings_property(seed):
    """Property test (hypothesis-style seeded loop): ANY interleaving
    of allocate / ref / free / preempt-style bulk-free ends with a full
    free list and zero refcount drift — including orderings the engine
    never produces today.  A shadow refcount model checks every
    intermediate state; `check_leaks()` must come back clean after the
    final teardown."""
    rng = np.random.RandomState(seed)
    pool = BlockPool(num_layers=1, num_blocks=16, block_size=4,
                     num_kv_heads=2, head_dim=8)
    shadow = {}                 # block id -> refcount (held blocks only)
    tables = []                 # simulated per-request block tables

    for _ in range(300):
        op = rng.randint(4)
        if op == 0:                                   # allocate
            n = int(rng.randint(1, 5))
            got = pool.allocate(n)
            if n > pool.num_blocks - sum(
                    1 for r in shadow.values() if r > 0):
                # more than physically free: must refuse, not corrupt
                assert got is None or len(got) == n
            if got is None:
                continue
            assert len(set(got)) == n
            assert not any(b in shadow and shadow[b] > 0 for b in got)
            for b in got:
                shadow[b] = 1
            tables.append(list(got))
        elif op == 1 and tables:                      # ref (share)
            t = tables[int(rng.randint(len(tables)))]
            pool.ref(t)
            tables.append(list(t))
            for b in t:
                shadow[b] += 1
        elif op == 2 and tables:                      # free one table
            t = tables.pop(int(rng.randint(len(tables))))
            pool.free(t)
            for b in t:
                shadow[b] -= 1
        elif op == 3 and tables:                      # preempt: bulk free
            k = int(rng.randint(1, len(tables) + 1))
            for _ in range(k):
                t = tables.pop()
                pool.free(t)
                for b in t:
                    shadow[b] -= 1
        # shadow model and pool must agree at EVERY step
        held = sum(1 for r in shadow.values() if r > 0)
        assert pool.free_blocks == pool.num_blocks - held
        assert pool._refs == [shadow.get(b, 0)
                              for b in range(pool.num_blocks)]
        assert all(r >= 0 for r in shadow.values())

    for t in tables:            # teardown: everything goes home
        pool.free(t)
    assert pool.check_leaks() == ([], [])
    assert pool.free_blocks == pool.num_blocks
    assert sorted(pool._free) == list(range(pool.num_blocks))


# ===================================================================
# preemption and resume mid-decode
# ===================================================================
def test_preemption_resume_mid_decode_parity(gpt):
    m = gpt
    rng = np.random.RandomState(2)
    prompts = [rng.randint(0, 64, size=n).tolist()
               for n in (7, 11, 5, 9, 6, 4)]
    refs = [_seq_ref(m, p, 8) for p in prompts]
    # 6 blocks of 4 tokens cannot hold 6 requests of 12-19 tokens:
    # preemption MUST fire, and evicted requests re-prefill + resume
    eng = LLMEngine(m, num_blocks=6, block_size=4, max_running=6,
                    prefill_chunk=8)
    reqs = [eng.add_request(p, max_new_tokens=8) for p in prompts]
    eng.run()
    assert sum(r.preemptions for r in reqs) >= 1
    assert [list(r.generated) for r in reqs] == refs
    assert eng.pool.free_blocks == eng.pool.num_blocks


def test_preempted_request_keeps_queue_front(gpt):
    eng = LLMEngine(gpt, num_blocks=4, block_size=4, max_running=2,
                    prefill_chunk=8)
    a = eng.add_request([1] * 9, max_new_tokens=6)
    b = eng.add_request([2] * 9, max_new_tokens=6)
    eng.run()
    assert a.finish_reason == "length" and b.finish_reason == "length"
    leaked, bad = eng.pool.check_leaks()
    assert not leaked and not bad


# ===================================================================
# paged attention: pallas (interpret) vs the jnp gather fallback
# ===================================================================
def test_paged_attention_pallas_matches_fallback():
    import jax.numpy as jnp
    from paddle_tpu.ops.nn_kernels import paged_attention_k
    from paddle_tpu.ops.pallas import paged_attention as pa

    rng = np.random.RandomState(0)
    # D = 128: the kernel serves lane-aligned head dims only (the pool
    # is never padded in-call; others take the gather fallback)
    B, H, Hkv, D, bs, N, M = 3, 4, 2, 128, 8, 12, 4
    q = jnp.asarray(rng.randn(B, 1, H, D), jnp.float32)
    kp = jnp.asarray(rng.randn(N, bs, Hkv, D), jnp.float32)
    vp = jnp.asarray(rng.randn(N, bs, Hkv, D), jnp.float32)
    tables = jnp.asarray(rng.permutation(N)[:B * M].reshape(B, M),
                         jnp.int32)
    pos = jnp.asarray([5, 17, 30], jnp.int32)
    assert pa.supports(q.shape, kp.shape, q.dtype)
    ref = np.asarray(paged_attention_k(q, kp, vp, tables, pos))
    out = np.asarray(pa.paged_decode_attention(q, kp, vp, tables, pos + 1,
                                               interpret=True))
    np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-6)


def test_paged_attention_supports_gate():
    import jax.numpy as jnp
    from paddle_tpu.ops.pallas import paged_attention as pa
    ok = ((3, 1, 4, 128), (12, 8, 2, 128))
    assert pa.supports(*ok, jnp.float32)
    assert not pa.supports((3, 2, 4, 128), ok[1], jnp.float32)  # prefill
    assert not pa.supports(ok[0], (12, 6, 2, 128), jnp.float32)  # bs % 8
    assert not pa.supports(ok[0], (12, 8, 3, 128), jnp.float32)  # H % Hkv
    assert not pa.supports((3, 1, 4, 64), (12, 8, 2, 64),
                           jnp.float32)                # unaligned head_dim
    assert not pa.supports(ok[0], ok[1], jnp.int32)


def test_paged_prefill_matches_dense_forward(gpt):
    """One whole-prompt paged forward == the plain dense forward (the
    foundation of the engine's token parity)."""
    import jax.numpy as jnp
    from paddle_tpu.tensor import Tensor
    m = gpt
    m.eval()
    ids = pt.randint(0, 64, [1, 6])
    with pt.no_grad():
        full = m(ids).numpy()
        pool = BlockPool.for_model(m, num_blocks=8, block_size=4)
        table = np.zeros((1, 2), np.int32)
        table[0] = [3, 5]
        caches = [{"k": Tensor._from_array(pool.k[i]),
                   "v": Tensor._from_array(pool.v[i]),
                   "table": Tensor._from_array(jnp.asarray(table)),
                   "pos": Tensor._from_array(jnp.zeros(1, jnp.int32)),
                   "limit": Tensor._from_array(
                       jnp.full((1,), 6, jnp.int32))}
                  for i in range(pool.num_layers)]
        paged = m(ids, caches=caches).numpy()
    np.testing.assert_allclose(paged, full, rtol=2e-4, atol=2e-5)


# ===================================================================
# generate(): per-sequence EOS stop in a batch (serving-reuse fix)
# ===================================================================
def test_generate_batch_eos_per_sequence():
    m = _tiny_gpt()
    a = [1, 2, 3, 4, 5]
    b = [9, 8, 7, 6, 5]
    # pick an eos the FIRST row emits early but the second does not
    eos = _seq_ref(m, a, 1)[0]
    solo_b = _seq_ref(m, b, 6, eos=eos)
    batch = generate(m, pt.to_tensor(np.asarray([a, b], "int64")),
                     max_new_tokens=6, eos_token_id=eos).numpy()
    gen_a, gen_b = batch[0, 5:].tolist(), batch[1, 5:].tolist()
    # the finished row is eos-padded right of its stop, not garbage...
    assert all(t == eos for t in gen_a[gen_a.index(eos):])
    # ...and the unfinished row decodes exactly its solo trajectory
    assert gen_b[:len(solo_b)] == solo_b


# ===================================================================
# AOT artifacts: zero-compile warm replica start
# ===================================================================
def test_serving_aot_roundtrip_zero_compile(gpt, tmp_path):
    import json
    prompts = [[1, 2, 3, 4, 5], [7] * 11]
    kw = dict(num_blocks=16, block_size=8, max_running=4,
              prefill_chunk=16)
    eng = LLMEngine(gpt, **kw)
    refs = eng.generate_batch(prompts, max_new_tokens=5)
    export_serving_artifacts(eng, str(tmp_path),
                             prompt_lens=[len(p) for p in prompts])

    warm = LLMEngine(gpt, **kw)
    keys = load_serving_artifacts(warm, str(tmp_path))
    assert ("decode",) in keys
    assert warm.generate_batch(prompts, max_new_tokens=5) == refs
    # the warm replica never traced/compiled a live program
    assert warm._programs == {}

    # a stamp mismatch must refuse WITH the reason (strict=True raises)
    man = os.path.join(str(tmp_path), "serving_manifest.json")
    with open(man) as f:
        data = json.load(f)
    data["stamp"]["jax"] = "0.0.0-somewhere-else"
    with open(man, "w") as f:
        json.dump(data, f)
    cold = LLMEngine(gpt, **kw)
    with pytest.warns(UserWarning, match="jax version"):
        assert load_serving_artifacts(cold, str(tmp_path)) == []
    from paddle_tpu.jit.save_load import AOTIncompatible
    with pytest.raises(AOTIncompatible):
        load_serving_artifacts(cold, str(tmp_path), strict=True)


# ===================================================================
# chaos sites + the overload drill (tier-1 wiring of --serving)
# ===================================================================
def test_pool_exhausted_chaos_site():
    from paddle_tpu.resilience import chaos
    pool = BlockPool(1, 8, 4, 2, 8)
    with chaos.scoped("serving.pool_exhausted@1"):
        assert pool.allocate(1) is None     # injected refusal
        a = pool.allocate(1)                # next hit is clean
        assert len(a) == 1
    pool.free(a)


def test_chaos_check_serving_inprocess():
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "chaos_check", os.path.join(REPO, "tools", "chaos_check.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    buf = io.StringIO()
    assert mod.run_serving(out=buf) == 0, buf.getvalue()
    assert "zero block leaks" in buf.getvalue()


# ===================================================================
# request validation
# ===================================================================
def test_add_request_validation(gpt):
    eng = LLMEngine(gpt, num_blocks=4, block_size=4)   # 16 token pool
    with pytest.raises(ValueError):
        eng.add_request([], max_new_tokens=4)
    with pytest.raises(ValueError):
        eng.add_request([1] * 60, max_new_tokens=10)  # > max_model_len
    with pytest.raises(PoolExhausted):
        eng.add_request([1] * 20, max_new_tokens=10)  # > whole pool
