"""Orthogonal/Dirac initializers + CyclicLR (reference:
nn/initializer/{orthogonal,dirac}.py, optimizer/lr.py CyclicLR)."""
import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F


def test_orthogonal_rows_orthonormal():
    pt.seed(0)
    p = pt.parameter(np.zeros((8, 8), np.float32))
    nn.initializer.Orthogonal()(p)
    np.testing.assert_allclose(p.numpy() @ p.numpy().T, np.eye(8),
                               atol=1e-5)
    tall = pt.parameter(np.zeros((4, 16), np.float32))
    nn.initializer.Orthogonal(gain=2.0)(tall)
    np.testing.assert_allclose(tall.numpy() @ tall.numpy().T,
                               4.0 * np.eye(4), atol=1e-4)


def test_dirac_identity_conv():
    w = pt.parameter(np.zeros((3, 3, 3, 3), np.float32))
    nn.initializer.Dirac()(w)
    x = pt.randn([1, 3, 6, 6])
    np.testing.assert_allclose(F.conv2d(x, w, padding=1).numpy(),
                               x.numpy(), atol=1e-6)


def test_cyclic_lr_policies():
    sch = pt.optimizer.lr.CyclicLR(base_learning_rate=0.1,
                                   max_learning_rate=0.5, step_size_up=4)
    lrs = []
    for _ in range(16):
        lrs.append(sch())
        sch.step()
    assert abs(lrs[0] - 0.1) < 1e-6
    assert abs(max(lrs) - 0.5) < 1e-6
    assert abs(lrs[8] - 0.1) < 1e-6  # cycle restarts at base

    sch2 = pt.optimizer.lr.CyclicLR(0.1, 0.5, 2, mode="triangular2")
    peaks = []
    for _ in range(12):
        peaks.append(sch2())
        sch2.step()
    # second cycle's peak is half the first amplitude
    assert abs(peaks[2] - 0.5) < 1e-6
    assert abs(peaks[6] - 0.3) < 1e-6

    with pytest.raises(ValueError, match="mode"):
        pt.optimizer.lr.CyclicLR(0.1, 0.5, 2, mode="nope")
    with pytest.raises(ValueError, match="positive"):
        pt.optimizer.lr.CyclicLR(0.1, 0.5, 0)
