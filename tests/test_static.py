"""paddle.static surface (reference: python/paddle/static) — Program capture,
Executor train/infer runs, clone(for_test), save/load_inference_model."""
import os

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu.framework import static_graph as SG


@pytest.fixture
def static_mode():
    paddle.enable_static()
    SG.reset()
    yield
    SG.reset()
    paddle.disable_static()


def _build_regression():
    main, startup = paddle.static.Program(), paddle.static.Program()
    with paddle.static.program_guard(main, startup):
        x = paddle.static.data("x", [None, 4], "float32")
        y = paddle.static.data("y", [None, 1], "float32")
        model = nn.Sequential(nn.Linear(4, 16), nn.ReLU(), nn.Linear(16, 1))
        pred = model(x)
        loss = F.mse_loss(pred, y)
        opt = paddle.optimizer.Adam(learning_rate=0.05,
                                    parameters=model.parameters())
        opt.minimize(loss)
    return main, startup, x, y, pred, loss


def test_static_training_converges(static_mode):
    main, startup, x, y, pred, loss = _build_regression()
    exe = paddle.static.Executor()
    exe.run(startup)
    rng = np.random.RandomState(0)
    w = rng.randn(4, 1).astype(np.float32)
    losses = []
    for _ in range(30):
        xb = rng.randn(16, 4).astype(np.float32)
        (lv,) = exe.run(main, feed={"x": xb, "y": xb @ w},
                        fetch_list=[loss])
        losses.append(float(lv))
    assert losses[-1] < losses[0] * 0.1


def test_static_clone_for_test_is_pure(static_mode):
    main, startup, x, y, pred, loss = _build_regression()
    test_prog = main.clone(for_test=True)
    exe = paddle.static.Executor()
    feed = {"x": np.ones((3, 4), np.float32),
            "y": np.zeros((3, 1), np.float32)}
    (p1,) = exe.run(test_prog, feed=feed, fetch_list=[pred])
    (p2,) = exe.run(test_prog, feed=feed, fetch_list=[pred])
    np.testing.assert_allclose(p1, p2)  # no optimizer side effects


def test_static_batch_polymorphism(static_mode):
    """None dims accept any batch size (one jit per feed signature)."""
    main, startup, x, y, pred, loss = _build_regression()
    test_prog = main.clone(for_test=True)
    exe = paddle.static.Executor()
    for b in (2, 5):
        (pv,) = exe.run(test_prog,
                        feed={"x": np.ones((b, 4), np.float32),
                              "y": np.zeros((b, 1), np.float32)},
                        fetch_list=[pred])
        assert pv.shape == (b, 1)


def test_static_missing_feed_raises(static_mode):
    main, startup, x, y, pred, loss = _build_regression()
    exe = paddle.static.Executor()
    with pytest.raises(ValueError, match="feed"):
        exe.run(main.clone(for_test=True),
                feed={"x": np.ones((2, 4), np.float32)},
                fetch_list=[loss])


def test_static_save_load_inference_model(static_mode, tmp_path):
    main, startup, x, y, pred, loss = _build_regression()
    exe = paddle.static.Executor()
    feed = {"x": np.ones((3, 4), np.float32),
            "y": np.zeros((3, 1), np.float32)}
    (pv,) = exe.run(main.clone(for_test=True), feed=feed, fetch_list=[pred])
    path = os.path.join(str(tmp_path), "inf")
    with paddle.static.program_guard(main, startup):
        paddle.static.save_inference_model(path, [x], [pred], exe)
    prog, feed_names, fetch_targets = paddle.static.load_inference_model(path)
    assert feed_names == ["x"]
    (out,) = exe.run(prog, feed={"x": feed["x"]}, fetch_list=fetch_targets)
    np.testing.assert_allclose(out, pv, rtol=1e-5)


def test_static_nn_fc(static_mode):
    exe = paddle.static.Executor()
    with paddle.static.program_guard(paddle.static.Program()):
        x2 = paddle.static.data("x2", [None, 8], "float32")
        h = paddle.static.nn.fc(x2, 4, activation="relu")
        (hv,) = exe.run(feed={"x2": np.ones((2, 8), np.float32)},
                        fetch_list=[h])
    assert hv.shape == (2, 4) and (hv >= 0).all()


def test_dynamic_mode_untouched_after_static(static_mode):
    _build_regression()
    paddle.disable_static()
    assert paddle.in_dynamic_mode()
    t = paddle.randn([2, 3])
    t.stop_gradient = False
    s = (t * 2.0).sum()
    s.backward()
    assert t.grad is not None


def test_static_minimize_only_touches_optimizer_params(static_mode):
    """Leaves outside the optimizer's parameter list stay frozen."""
    main = paddle.static.Program()
    with paddle.static.program_guard(main):
        x = paddle.static.data("x", [None, 4], "float32")
        a = nn.Linear(4, 4)
        b = nn.Linear(4, 1)
        loss = (b(a(x)) ** 2).mean()
        opt = paddle.optimizer.SGD(learning_rate=0.5,
                                   parameters=a.parameters())
        opt.minimize(loss)
    exe = paddle.static.Executor()
    aw0, bw0 = a.weight.numpy().copy(), b.weight.numpy().copy()
    for _ in range(2):
        exe.run(main, feed={"x": np.ones((4, 4), np.float32)},
                fetch_list=[loss])
    assert not np.allclose(a.weight.numpy(), aw0)
    np.testing.assert_array_equal(b.weight.numpy(), bw0)


def test_static_optimizer_state_dict_has_moments(static_mode):
    main, startup, x, y, pred, loss = _build_regression()
    exe = paddle.static.Executor()
    opt = main._train["optimizer"]
    for _ in range(2):
        exe.run(main, feed={"x": np.ones((4, 4), np.float32),
                            "y": np.zeros((4, 1), np.float32)},
                fetch_list=[loss])
    sd = opt.state_dict()
    moment_keys = [k for k in sd if "/" in k]
    assert moment_keys, "Adam moments must survive static training"
    assert any(np.abs(np.asarray(sd[k])).sum() > 0 for k in moment_keys)


def test_static_fc_num_flatten_dims(static_mode):
    exe = paddle.static.Executor()
    with paddle.static.program_guard(paddle.static.Program()):
        x = paddle.static.data("x", [None, 3, 5], "float32")
        h = paddle.static.nn.fc(x, 7)  # flattens [3,5] -> 15
        (hv,) = exe.run(feed={"x": np.ones((2, 3, 5), np.float32)},
                        fetch_list=[h])
    assert hv.shape == (2, 7)


def test_static_dropout_fresh_mask_per_run(static_mode):
    """The build-time RNG key must not bake: each Executor.run rethreads
    randomness, so two runs produce different dropout masks."""
    main = paddle.static.Program()
    with paddle.static.program_guard(main):
        x = paddle.static.data("x", [None, 64], "float32")
        h = paddle.nn.functional.dropout(x, 0.5, training=True)
    exe = paddle.static.Executor()
    feed = {"x": np.ones((2, 64), np.float32)}
    (a,) = exe.run(main, feed=feed, fetch_list=[h])
    (b,) = exe.run(main, feed=feed, fetch_list=[h])
    assert not np.array_equal(a, b)


def test_static_clone_for_test_disables_dropout(static_mode):
    main = paddle.static.Program()
    with paddle.static.program_guard(main):
        x = paddle.static.data("x", [None, 8], "float32")
        h = paddle.nn.functional.dropout(x, 0.9, training=True)
    test_prog = main.clone(for_test=True)
    exe = paddle.static.Executor()
    (hv,) = exe.run(test_prog, feed={"x": np.ones((2, 8), np.float32)},
                    fetch_list=[h])
    np.testing.assert_allclose(hv, 1.0)  # identity at inference


def test_static_fetch_from_wrong_program_raises(static_mode):
    p1 = paddle.static.Program()
    with paddle.static.program_guard(p1):
        x1 = paddle.static.data("x", [None, 2], "float32")
        h1 = x1 * 2.0
    p2 = paddle.static.Program()
    with paddle.static.program_guard(p2):
        paddle.static.data("x", [None, 2], "float32")
    exe = paddle.static.Executor()
    with pytest.raises(ValueError, match="not recorded"):
        exe.run(p2, feed={"x": np.ones((1, 2), np.float32)},
                fetch_list=[h1])


def test_static_batch_norm_warns(static_mode):
    main = paddle.static.Program()
    with paddle.static.program_guard(main):
        x = paddle.static.data("x", [None, 4], "float32")
        bn = nn.BatchNorm1D(4)
        with pytest.warns(UserWarning, match="RUNNING statistics"):
            bn(x)


def test_data_requires_static_mode():
    assert paddle.in_dynamic_mode()
    with pytest.raises(RuntimeError, match="enable_static"):
        paddle.static.data("q", [None, 2], "float32")


def test_creation_rng_rethreads_per_run():
    """Round-3: paddle.uniform/randn in static mode are per-run random
    (round 2 froze them into build-time constants — VERDICT weak #7)."""
    import numpy as np
    import paddle_tpu as pt
    import paddle_tpu.static as st
    from paddle_tpu.framework import static_graph as sg

    pt.enable_static()
    try:
        sg.reset()
        x = st.data("x", [2], "float32")
        y = x + pt.uniform([2], min=0.0, max=1.0)
        z = x + pt.randn([2])
        exe = st.Executor()
        feed = {"x": np.zeros(2, np.float32)}
        y1, z1 = exe.run(feed=feed, fetch_list=[y, z])
        y2, z2 = exe.run(feed=feed, fetch_list=[y, z])
        assert not np.allclose(np.asarray(y1), np.asarray(y2))
        assert not np.allclose(np.asarray(z1), np.asarray(z2))
        assert (np.asarray(y1) >= 0).all() and (np.asarray(y1) <= 1).all()
    finally:
        pt.disable_static()
        sg.reset()


def test_creation_rng_chains_and_persistable_buffers():
    """Derived creation chains (bernoulli(uniform), randn*2) must stay
    per-run random; persistable buffers built from randn must replay as
    LIVE leaves (review-round regressions)."""
    import numpy as np
    import paddle_tpu as pt
    import paddle_tpu.static as st
    from paddle_tpu.framework import static_graph as sg

    pt.enable_static()
    try:
        sg.reset()
        x = st.data("x", [4], "float32")
        m = pt.bernoulli(pt.uniform([4], min=0.3, max=0.7))
        y = x + m
        z = x + pt.randn([4]) * 2.0
        buf = pt.randn([4])
        buf.persistable = True
        used = x + buf
        exe = st.Executor()
        feed = {"x": np.zeros(4, np.float32)}
        y1, z1, b1 = exe.run(feed=feed, fetch_list=[y, z, used])
        y2, z2, b2 = exe.run(feed=feed, fetch_list=[y, z, used])
        assert not np.array_equal(np.asarray(y1), np.asarray(y2))
        assert not np.array_equal(np.asarray(z1), np.asarray(z2))
        np.testing.assert_array_equal(np.asarray(b1), np.asarray(b2))
    finally:
        pt.disable_static()
        sg.reset()
