"""Ring attention x pallas flash fusion (VERDICT r3 item 5; reference
analog: paddle incubate RingFlashAttention over NCCL send/recv).

Per KV-ring step the pallas flash kernel computes one normalized block
(o, lse); blocks merge by log-sum-exp.  Backward reuses the flash
backward with the GLOBAL lse — each step's (dq, dk, dv) are exact
partials and (dk, dv) ride the ring with their kv shard.  CI runs the
kernels in interpret mode on the virtual CPU mesh (the mosaic compile is
exercised on-chip by the bench probe)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from paddle_tpu.distributed.ring_attention import ring_attention


def _mesh(n=4):
    devs = jax.devices()
    if len(devs) < n:
        pytest.skip(f"needs {n} devices")
    return Mesh(np.array(devs[:n]), ("mp",))


def _full_ref(q, k, v, causal):
    B, L, H, D = q.shape
    Hkv = k.shape[2]
    kk, vv = k, v
    if Hkv != H:
        g = H // Hkv
        kk = jnp.repeat(k, g, axis=2)
        vv = jnp.repeat(v, g, axis=2)
    s = jnp.einsum("blhd,bmhd->bhlm", q, kk).astype(jnp.float32) / (D**0.5)
    if causal:
        m = jnp.tril(jnp.ones((L, L), bool))
        s = jnp.where(m[None, None], s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhlm,bmhd->blhd", p.astype(v.dtype), vv)


def _qkv(H, Hkv, B=2, L=128, D=16, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    return (jax.random.normal(ks[0], (B, L, H, D), jnp.float32),
            jax.random.normal(ks[1], (B, L, Hkv, D), jnp.float32),
            jax.random.normal(ks[2], (B, L, Hkv, D), jnp.float32))


@pytest.mark.parametrize("H,Hkv,causal", [(4, 4, True), (4, 4, False),
                                          (8, 2, True), (8, 4, False)])
def test_ring_flash_matches_full_attention(H, Hkv, causal):
    mesh = _mesh()
    q, k, v = _qkv(H, Hkv)
    ref = _full_ref(q, k, v, causal)
    out = jax.jit(lambda a, b, c: ring_attention(
        a, b, c, mesh=mesh, causal=causal, impl="interpret"))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("H,Hkv,causal", [(4, 4, True), (8, 2, True),
                                          (4, 4, False)])
def test_ring_flash_grads_match_full_attention(H, Hkv, causal):
    mesh = _mesh()
    q, k, v = _qkv(H, Hkv, seed=3)

    def loss_ref(q, k, v):
        return jnp.sum(jnp.sin(_full_ref(q, k, v, causal)))

    def loss_ring(q, k, v):
        return jnp.sum(jnp.sin(ring_attention(
            q, k, v, mesh=mesh, causal=causal, impl="interpret")))

    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    gf = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    for a, b, name in zip(gr, gf, "q k v".split()):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=3e-4, atol=3e-5,
                                   err_msg=f"d{name}")


def test_ring_flash_matches_einsum_ring_path():
    """The two ring implementations (einsum streaming-softmax vs pallas
    per-step kernel) must agree exactly — same math, different engines."""
    mesh = _mesh()
    q, k, v = _qkv(8, 2, seed=5)
    a = jax.jit(lambda *t: ring_attention(*t, mesh=mesh, causal=True,
                                          impl="einsum"))(q, k, v)
    b = jax.jit(lambda *t: ring_attention(*t, mesh=mesh, causal=True,
                                          impl="interpret"))(q, k, v)
    np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                               rtol=2e-5, atol=2e-5)
