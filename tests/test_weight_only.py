"""paddle.nn.quant weight-only quantization (reference:
python/paddle/nn/quant/quantized_linear.py).

Contracts under test: int8/int4 quantize->linear tracks the fp32 linear
within quantization error; nibble packing round-trips exactly; a
converted GPT still generates sensibly with 2x/4x smaller weight bytes.
"""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.nn.quant import (WeightOnlyLinear, convert_to_weight_only,
                                 weight_only_linear, weight_quantize)


class TestWeightQuantize:
    def test_int8_roundtrip_error_bound(self):
        rng = np.random.RandomState(0)
        w = pt.to_tensor(rng.randn(64, 32).astype(np.float32) * 0.1)
        q, s = weight_quantize(w, algo="weight_only_int8")
        assert str(q.dtype) == "int8" and q.shape == [64, 32]
        deq = q.numpy().astype(np.float32) * s.numpy()[None, :] / 127.0
        err = np.abs(deq - w.numpy()).max()
        assert err <= s.numpy().max() / 127.0 + 1e-7

    def test_int4_pack_unpack_exact(self):
        from paddle_tpu.nn.quant import _unpack_int4
        rng = np.random.RandomState(1)
        w = pt.to_tensor(rng.randn(31, 8).astype(np.float32))  # odd k
        q, s = weight_quantize(w, algo="weight_only_int4")
        assert q.shape == [16, 8]            # ceil(31/2)
        unpacked = np.asarray(_unpack_int4(q._array, 31))
        ref = np.clip(np.round(w.numpy() / s.numpy()[None, :] * 7.0),
                      -7, 7).astype(np.int8)
        np.testing.assert_array_equal(unpacked, ref)

    def test_weight_only_linear_matches_fp(self):
        rng = np.random.RandomState(2)
        x = pt.to_tensor(rng.randn(4, 64).astype(np.float32))
        w = pt.to_tensor(rng.randn(64, 32).astype(np.float32) * 0.05)
        b = pt.to_tensor(rng.randn(32).astype(np.float32))
        ref = (x.numpy() @ w.numpy()) + b.numpy()
        for algo, rtol in (("weight_only_int8", 2e-2),
                           ("weight_only_int4", 2e-1)):
            q, s = weight_quantize(w, algo=algo)
            y = weight_only_linear(x, q, bias=b, weight_scale=s,
                                   weight_dtype=algo[-4:])
            np.testing.assert_allclose(y.numpy(), ref, rtol=rtol,
                                       atol=rtol)

    def test_weight_only_layer_from_linear(self):
        pt.seed(3)
        lin = pt.nn.Linear(16, 8)
        wol = WeightOnlyLinear.from_linear(lin, algo="weight_only_int8")
        x = pt.rand([2, 16])
        np.testing.assert_allclose(wol(x).numpy(), lin(x).numpy(),
                                   rtol=2e-2, atol=2e-2)
        # weight bytes shrink 4x vs fp32 storage
        assert wol.quant_weight.numpy().nbytes * 4 == \
            lin.weight.numpy().nbytes

    def test_convert_model_and_generate(self):
        pt.seed(4)
        from paddle_tpu.text import GPTConfig, GPTForCausalLM
        from paddle_tpu.text.generation import generate
        cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=2,
                        num_heads=4, max_position_embeddings=32,
                        hidden_dropout=0.0, attention_dropout=0.0)
        m = GPTForCausalLM(cfg)
        m.eval()
        ids = pt.to_tensor(np.arange(8, dtype=np.int64)[None, :] % 64)
        with pt.no_grad():
            ref_logits = m(ids).numpy()
        n_lin_before = sum(isinstance(l, pt.nn.Linear)
                           for l in m.sublayers())
        convert_to_weight_only(m, algo="weight_only_int8")
        n_lin_after = sum(isinstance(l, pt.nn.Linear)
                          for l in m.sublayers())
        n_wol = sum(isinstance(l, WeightOnlyLinear) for l in m.sublayers())
        assert n_wol == n_lin_before and n_lin_after == 0
        with pt.no_grad():
            q_logits = m(ids).numpy()
        # quantization error stays small relative to logit scale
        denom = np.abs(ref_logits).max()
        assert np.abs(q_logits - ref_logits).max() / denom < 0.1
        out = generate(m, ids, max_new_tokens=4)
        assert out.shape == [1, 12]

    def test_state_dict_roundtrip_preserves_quant_weights(self):
        # regression: quant_weight must be a registered buffer or
        # checkpoints silently drop the int8 weights
        pt.seed(5)
        lin = pt.nn.Linear(8, 4)
        wol = WeightOnlyLinear.from_linear(lin)
        sd = wol.state_dict()
        assert any("quant_weight" in k for k in sd)
        fresh = WeightOnlyLinear(8, 4)
        missing, unexpected = fresh.set_state_dict(sd)
        assert not missing and not unexpected
        x = pt.rand([2, 8])
        np.testing.assert_allclose(fresh(x).numpy(), wol(x).numpy(),
                                   rtol=1e-6, atol=1e-6)

    def test_scale_required(self):
        q, s = weight_quantize(pt.rand([8, 4]))
        with pytest.raises(ValueError, match="weight_scale"):
            weight_only_linear(pt.rand([2, 8]), q)

    def test_skip_predicate(self):
        m = pt.nn.Sequential(pt.nn.Linear(4, 4), pt.nn.Linear(4, 4))
        convert_to_weight_only(m, skip=lambda name, l: name.endswith("1"))
        kinds = [type(l).__name__ for l in m]
        assert kinds == ["WeightOnlyLinear", "Linear"]

    @pytest.mark.parametrize("algo", ["weight_only_int8",
                                      "weight_only_int4"])
    def test_onnx_export_of_converted_model(self, tmp_path, algo):
        # a weight-only model serializes as DequantizeLinear + MatMul and
        # round-trips through the bundled evaluator (int4 unpacks into
        # the int8 initializer — ONNX has no nibble packing)
        from paddle_tpu import onnx as ponnx
        pt.seed(6)
        m = pt.nn.Sequential(pt.nn.Linear(8, 16), pt.nn.ReLU(),
                             pt.nn.Linear(16, 4))
        convert_to_weight_only(m, algo=algo)
        m.eval()
        x = pt.rand([3, 8])
        with pt.no_grad():
            want = m(x).numpy()
        p = ponnx.export(m, str(tmp_path / "wo"), input_spec=[x])
        model = ponnx.load(p)
        assert any(n.op_type == "DequantizeLinear"
                   for n in model.graph.node)
        # dead-initializer sweep: every initializer must be referenced
        # (no double-stored quantized weights)
        referenced = {i for n in model.graph.node for i in n.input}
        for t in model.graph.initializer:
            assert t.name in referenced, t.name
        got = ponnx.run(model, [x.numpy()])[0]
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)

    def test_grouped_scales_raise(self):
        w = pt.rand([8, 4])
        q, s = weight_quantize(w)
        with pytest.raises(NotImplementedError, match="group"):
            weight_only_linear(pt.rand([2, 8]), q, weight_scale=s,
                               group_size=64)


def test_weight_only_composes_with_jit_beam_search():
    """Serving composition: an int8 weight-only-converted GPT decodes
    through the jitted beam search (dequant fused into the matmuls
    inside the while_loop), token-exact vs its own eager beam."""
    from paddle_tpu.text import GPTConfig, GPTForCausalLM
    from paddle_tpu.text.generation import beam_search
    from paddle_tpu.text.decode import jit_beam_search
    pt.seed(7)
    cfg = GPTConfig(vocab_size=96, hidden_size=48, num_layers=2,
                    num_heads=4, max_position_embeddings=64,
                    hidden_dropout=0.0, attention_dropout=0.0,
                    tensor_parallel=False)
    m = GPTForCausalLM(cfg)
    m.eval()
    convert_to_weight_only(m, algo="weight_only_int8")
    ids = pt.to_tensor(np.array([[5, 17, 40, 3]], np.int64))
    want = beam_search(m, ids, beam_size=3, max_new_tokens=6).numpy()
    got = jit_beam_search(m, ids, beam_size=3, max_new_tokens=6).numpy()
    np.testing.assert_array_equal(got, want)
