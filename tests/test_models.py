"""Model zoo tests (SURVEY §4): shapes + tiny overfit + generation."""
import math

import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F


def test_resnet18_forward():
    m = pt.vision.models.resnet18(num_classes=10)
    m.eval()
    x = pt.randn([2, 3, 64, 64])
    assert m(x).shape == [2, 10]


def test_resnet50_forward():
    m = pt.vision.models.resnet50(num_classes=10)
    m.eval()
    x = pt.randn([1, 3, 64, 64])
    assert m(x).shape == [1, 10]


def test_resnet_s2d_stem_parity():
    """space-to-depth stem (bench MXU trick) is numerically identical to
    the plain 7x7/s2 stem — same parameters, same outputs."""
    from paddle_tpu.ops.dispatch import call_raw
    import jax.numpy as jnp
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(2, 3, 32, 32), jnp.float32)
    w = jnp.asarray(rng.randn(16, 3, 7, 7), jnp.float32)
    ref = call_raw("conv2d", x, w, stride=2, padding=3)
    s2d = call_raw("s2d_stem_conv", x, w)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(s2d),
                               rtol=1e-4, atol=1e-4)

    pt.seed(0)
    m1 = pt.vision.models.resnet50(num_classes=10)
    m2 = pt.vision.models.resnet50(num_classes=10, s2d_stem=True)
    m2.set_state_dict(m1.state_dict())
    m1.eval(); m2.eval()
    xt = pt.randn([2, 3, 64, 64])
    np.testing.assert_allclose(m1(xt).numpy(), m2(xt).numpy(),
                               rtol=2e-3, atol=2e-3)


def test_lenet():
    m = pt.vision.models.LeNet()
    assert m(pt.randn([2, 1, 28, 28])).shape == [2, 10]


def test_mobilenet_v2():
    m = pt.vision.models.mobilenet_v2(num_classes=10)
    m.eval()
    assert m(pt.randn([1, 3, 64, 64])).shape == [1, 10]


def _tiny_gpt(**kw):
    from paddle_tpu.text import GPTConfig, GPTForCausalLM
    cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=2, num_heads=4,
                    max_position_embeddings=32, tensor_parallel=False, **kw)
    return GPTForCausalLM(cfg)


def test_gpt_forward():
    m = _tiny_gpt()
    ids = pt.randint(0, 64, [2, 16])
    assert m(ids).shape == [2, 16, 64]


def test_gpt_overfit():
    pt.seed(0)
    m = _tiny_gpt(hidden_dropout=0.0, attention_dropout=0.0)
    opt = pt.optimizer.Adam(learning_rate=1e-2, parameters=m.parameters())
    ids = pt.randint(0, 64, [1, 12])
    labels = pt.randint(0, 64, [1, 12])
    from paddle_tpu.text import gpt_loss_fn
    step = pt.jit.train_step(m, gpt_loss_fn, opt)
    losses = [float(step(ids, labels)) for _ in range(30)]
    assert losses[-1] < losses[0] * 0.5, losses[::10]


def test_gpt_recompute_matches():
    pt.seed(0)
    m1 = _tiny_gpt(hidden_dropout=0.0, attention_dropout=0.0)
    pt.seed(0)
    m2 = _tiny_gpt(hidden_dropout=0.0, attention_dropout=0.0,
                   use_recompute=True)
    m2.set_state_dict(m1.state_dict())
    ids = pt.randint(0, 64, [1, 8])
    l1 = F.cross_entropy(m1(ids), ids)
    l2 = F.cross_entropy(m2(ids), ids)
    np.testing.assert_allclose(float(l1), float(l2), rtol=1e-5)
    l1.backward(); l2.backward()
    p1 = dict(m1.named_parameters())
    p2 = dict(m2.named_parameters())
    for n in p1:
        np.testing.assert_allclose(p1[n].grad.numpy(), p2[n].grad.numpy(),
                                   rtol=1e-4, atol=1e-6)


def test_gpt_generation():
    m = _tiny_gpt()
    ids = pt.randint(0, 64, [2, 4])
    out = m.generate(ids, max_new_tokens=5)
    assert out.shape == [2, 9]
    out2 = m.generate(ids, max_new_tokens=5, do_sample=True, top_k=10,
                      top_p=0.9, temperature=0.8)
    assert out2.shape == [2, 9]


def test_gpt_kv_cache_matches_full_forward():
    m = _tiny_gpt(hidden_dropout=0.0, attention_dropout=0.0)
    m.eval()
    ids = pt.randint(0, 64, [1, 6])
    full_logits = m(ids)
    caches = m.new_caches(1)
    with pt.no_grad():
        l1 = m(ids[:, :4], caches=caches)
        l2 = m(ids[:, 4:5], caches=caches)
        l3 = m(ids[:, 5:6], caches=caches)
    np.testing.assert_allclose(l3.numpy()[:, 0], full_logits.numpy()[:, 5],
                               rtol=1e-3, atol=1e-4)


def test_bert_forward():
    from paddle_tpu.text import BertConfig, BertModel, \
        BertForSequenceClassification
    cfg = BertConfig(vocab_size=100, hidden_size=32, num_hidden_layers=2,
                     num_attention_heads=4, intermediate_size=64,
                     max_position_embeddings=32)
    m = BertModel(cfg)
    ids = pt.randint(0, 100, [2, 10])
    seq, pooled = m(ids)
    assert seq.shape == [2, 10, 32]
    assert pooled.shape == [2, 32]
    clf = BertForSequenceClassification(cfg, num_classes=3)
    assert clf(ids).shape == [2, 3]


def test_bert_attention_mask():
    from paddle_tpu.text import BertConfig, BertModel
    cfg = BertConfig(vocab_size=100, hidden_size=32, num_hidden_layers=1,
                     num_attention_heads=4, intermediate_size=64,
                     max_position_embeddings=32, hidden_dropout_prob=0.0,
                     attention_probs_dropout_prob=0.0)
    m = BertModel(cfg)
    m.eval()
    ids = pt.randint(0, 100, [1, 8])
    mask = pt.to_tensor([[1, 1, 1, 1, 1, 1, 0, 0]])
    seq_m, _ = m(ids, attention_mask=mask)
    assert seq_m.shape == [1, 8, 32]


def test_llama_forward_and_gqa():
    from paddle_tpu.text import LlamaConfig, LlamaForCausalLM
    cfg = LlamaConfig.from_preset("llama-tiny", vocab_size=64,
                                  num_kv_heads=2, tensor_parallel=False)
    m = LlamaForCausalLM(cfg)
    ids = pt.randint(0, 64, [2, 8])
    assert m(ids).shape == [2, 8, 64]


def test_llama_kv_cache():
    from paddle_tpu.text import LlamaConfig, LlamaForCausalLM
    cfg = LlamaConfig.from_preset("llama-tiny", vocab_size=64,
                                  tensor_parallel=False)
    m = LlamaForCausalLM(cfg)
    m.eval()
    ids = pt.randint(0, 64, [1, 6])
    full = m(ids)
    caches = m.new_caches(1)
    with pt.no_grad():
        m(ids[:, :5], caches=caches)
        last = m(ids[:, 5:6], caches=caches)
    np.testing.assert_allclose(last.numpy()[:, 0], full.numpy()[:, 5],
                               rtol=1e-3, atol=1e-4)


def test_ernie_forward():
    from paddle_tpu.text import ErnieConfig, ErnieModel, \
        ErnieForSequenceClassification
    cfg = ErnieConfig(vocab_size=100, hidden_size=32, num_hidden_layers=2,
                      num_attention_heads=4, intermediate_size=64,
                      max_position_embeddings=32)
    m = ErnieModel(cfg)
    ids = pt.randint(0, 100, [2, 10])
    seq, pooled = m(ids)
    assert seq.shape == [2, 10, 32]
    clf = ErnieForSequenceClassification(cfg, num_classes=2)
    assert clf(ids).shape == [2, 2]


def test_ernie_to_static_inference():
    """The reference's ERNIE benchmark path: dy2static + fused graph."""
    from paddle_tpu.text import ErnieConfig, ErnieForSequenceClassification
    cfg = ErnieConfig(vocab_size=100, hidden_size=32, num_hidden_layers=2,
                      num_attention_heads=4, intermediate_size=64,
                      max_position_embeddings=32, hidden_dropout_prob=0.0,
                      attention_probs_dropout_prob=0.0)
    m = ErnieForSequenceClassification(cfg, num_classes=2)
    m.eval()
    ids = pt.randint(0, 100, [2, 10])
    eager = m(ids)
    static = pt.jit.to_static(m)
    np.testing.assert_allclose(static(ids).numpy(), eager.numpy(),
                               rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("ctor,size", [
    ("alexnet", 64), ("squeezenet1_0", 64), ("squeezenet1_1", 64),
    ("mobilenet_v1", 32), ("mobilenet_v3_small", 32),
    ("mobilenet_v3_large", 32), ("densenet121", 32),
    ("shufflenet_v2_x0_25", 32), ("inception_v3", 96),
])
def test_new_vision_models_forward(ctor, size):
    pt.seed(0)
    m = getattr(pt.vision.models, ctor)(num_classes=7)
    m.eval()
    x = pt.randn([2, 3, size, size])
    y = m(x)
    assert tuple(y.shape) == (2, 7)


def test_googlenet_train_and_eval_heads():
    pt.seed(0)
    m = pt.vision.models.googlenet(num_classes=5)
    m.eval()
    x = pt.randn([2, 3, 64, 64])
    out, aux1, aux2 = m(x)
    assert tuple(out.shape) == (2, 5)
    assert tuple(aux1.shape) == (2, 5) and tuple(aux2.shape) == (2, 5)


def test_new_model_trains_one_step():
    import paddle_tpu.nn.functional as F
    pt.seed(0)
    m = pt.vision.models.mobilenet_v3_small(num_classes=4, scale=0.5)
    opt = pt.optimizer.SGD(learning_rate=0.1, parameters=m.parameters())

    def loss_fn(model, x, y):
        return F.cross_entropy(model(x), y, reduction="mean")

    step = pt.jit.train_step(m, loss_fn, opt)
    x = pt.randn([4, 3, 32, 32]); y = pt.randint(0, 4, [4])
    l0 = float(step(x, y))
    l1 = float(step(x, y))  # second step exercises BN buffer round-trip
    assert math.isfinite(l0) and math.isfinite(l1)
    assert l1 < l0  # SGD on a fixed batch must descend


def test_feature_extractor_with_pool_contract():
    """num_classes=0, with_pool=True -> pooled [N, C, 1, 1] features for
    every zoo family (the with_pool kwarg must not be a silent no-op)."""
    pt.seed(0)
    x = pt.randn([1, 3, 64, 64])
    for ctor, c in [("squeezenet1_1", 512), ("googlenet", 1024),
                    ("densenet121", 1024)]:
        m = getattr(pt.vision.models, ctor)(num_classes=0, with_pool=True)
        m.eval()
        y = m(x)
        assert tuple(y.shape) == (1, c, 1, 1), (ctor, y.shape)
