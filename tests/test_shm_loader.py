"""Native shared-memory ring + multi-process DataLoader workers.

Mirrors the reference's multiprocess DataLoader tests
(test/legacy_test/test_multiprocess_dataloader_*.py): order preservation,
content equality vs single-process, worker crash propagation, iterable
sharding.
"""
import numpy as np
import pytest

from paddle_tpu.io import (DataLoader, Dataset, IterableDataset,
                           get_worker_info, native)

pytestmark = pytest.mark.skipif(not native.available(),
                                reason="native ring unavailable")


class NpDataset(Dataset):
    def __init__(self, n=37, dim=5):
        rng = np.random.default_rng(0)
        self.x = rng.standard_normal((n, dim)).astype(np.float32)

    def __len__(self):
        return len(self.x)

    def __getitem__(self, i):
        return self.x[i], np.int64(i)


def test_ring_roundtrip():
    from paddle_tpu.io.shm_loader import _Ring
    r = _Ring(1 << 16)
    for payload in (b"x", b"y" * 1000, b"z" * 30000):
        r.write(payload)
        n = r.next_len(1000)
        assert n == len(payload)
        assert r.read(n) == payload
    r.close_producer()
    assert r.next_len(1000) == -1
    r.release()


def test_ring_wraparound():
    from paddle_tpu.io.shm_loader import _Ring
    r = _Ring(native.LIB.ring_hdr_size() + 256)
    for i in range(50):  # forces many wraps of the 256-byte data region
        msg = bytes([i]) * (i % 100 + 1)
        r.write(msg)
        n = r.next_len(1000)
        assert r.read(n) == msg
    r.release()


def test_process_loader_matches_serial():
    ds = NpDataset()
    serial = [b for b in DataLoader(ds, batch_size=4, num_workers=0)]
    multi = [b for b in DataLoader(ds, batch_size=4, num_workers=3)]
    assert len(serial) == len(multi)
    for (xa, ia), (xb, ib) in zip(serial, multi):
        np.testing.assert_allclose(xa.numpy(), xb.numpy())
        np.testing.assert_array_equal(ia.numpy(), ib.numpy())


def test_process_loader_large_batches():
    class Big(Dataset):
        def __len__(self):
            return 8

        def __getitem__(self, i):
            return np.full((64, 64), i, np.float32)

    batches = [b for b in DataLoader(Big(), batch_size=2, num_workers=2)]
    assert len(batches) == 4
    assert batches[2].numpy()[0, 0, 0] == 4.0


def test_worker_exception_propagates():
    class Bad(Dataset):
        def __len__(self):
            return 10

        def __getitem__(self, i):
            if i == 5:
                raise ValueError("boom at 5")
            return np.zeros(3, np.float32)

    with pytest.raises(ValueError, match="boom at 5"):
        list(DataLoader(Bad(), batch_size=2, num_workers=2))


def test_iterable_dataset_self_sharding():
    # reference semantics: the dataset consults get_worker_info() and
    # yields its own shard; the loader must not shard a second time
    class Stream(IterableDataset):
        def __iter__(self):
            info = get_worker_info()
            data = np.arange(20, dtype=np.int64)
            if info is not None:
                data = data[info.id::info.num_workers]
            return iter(data)

    got = []
    for b in DataLoader(Stream(), batch_size=3, num_workers=2):
        got.extend(np.atleast_1d(b.numpy()).tolist())
    assert sorted(got) == list(range(20))


def test_iterable_dataset_naive_replicates():
    # a dataset that ignores worker info is replicated per worker,
    # matching the reference/torch loaders
    class Naive(IterableDataset):
        def __iter__(self):
            return iter(np.arange(6, dtype=np.int64))

    got = []
    for b in DataLoader(Naive(), batch_size=2, num_workers=2):
        got.extend(np.atleast_1d(b.numpy()).tolist())
    assert sorted(got) == sorted(list(range(6)) * 2)


def test_dead_worker_raises_not_hangs():
    import os as _os
    import signal as _signal

    class Suicide(Dataset):
        def __len__(self):
            return 8

        def __getitem__(self, i):
            if i == 4:  # batch 2 → worker 0's second batch
                _os.kill(_os.getpid(), _signal.SIGKILL)
            return np.zeros(2, np.float32)

    with pytest.raises(RuntimeError, match="died unexpectedly"):
        list(DataLoader(Suicide(), batch_size=2, num_workers=2))


def test_worker_init_fn_and_info():
    import os as _os

    def init_fn(worker_id):
        # runs in the forked child before any batch; visible to __getitem__
        _os.environ["_SHM_TEST_INIT"] = str(100 + worker_id)

    class Probe(Dataset):
        def __len__(self):
            return 4

        def __getitem__(self, i):
            info = get_worker_info()
            return (np.int64(info.id if info else -1),
                    np.int64(int(_os.environ.get("_SHM_TEST_INIT", "-1"))))

    out, inits = [], []
    for b in DataLoader(Probe(), batch_size=1, num_workers=2,
                        worker_init_fn=init_fn):
        out.extend(np.atleast_1d(b[0].numpy()).tolist())
        inits.extend(np.atleast_1d(b[1].numpy()).tolist())
    # batches 0,2 from worker 0; 1,3 from worker 1
    assert out == [0, 1, 0, 1]
    assert inits == [100, 101, 100, 101]  # init_fn ran in each worker


def test_device_backed_dataset_falls_back_to_threads():
    import paddle_tpu as pt
    from paddle_tpu.io import TensorDataset
    X = pt.randn([10, 4])
    dl = DataLoader(TensorDataset([X]), batch_size=5, num_workers=2)
    assert not dl._use_process_workers()
    assert len(list(dl)) == 2
