"""Qwen2 family + sliding-window attention (text/qwen.py; flash kernel
``window``; reference analogs: PaddleNLP transformers/qwen2, Mistral SWA).

Pinned: HF-checkpoint numeric parity for Qwen2 (biased q/k/v with the
rope row permutation applied to biases too), kernel-level SWA parity
against the banded XLA reference (fwd + all grads, GQA, ragged seq),
and cross-path decode agreement (teacher-forced vs eager concat-cache
vs jitted prealloc-cache greedy tokens under a window).
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as pt
from paddle_tpu.text import Qwen2Config, Qwen2ForCausalLM
from paddle_tpu.text.llama import LlamaConfig, LlamaForCausalLM


def test_qwen2_matches_transformers():
    import torch
    from paddle_tpu.text.convert import convert_hf_qwen2
    from transformers import Qwen2Config as HFC, Qwen2ForCausalLM as HFM

    torch.manual_seed(0)
    hf = HFM(HFC(vocab_size=128, hidden_size=64, intermediate_size=128,
                 num_hidden_layers=2, num_attention_heads=4,
                 num_key_value_heads=2, max_position_embeddings=64,
                 rope_theta=10000.0, rms_norm_eps=1e-6,
                 attention_dropout=0.0)).eval()
    pt.seed(0)
    ours = Qwen2ForCausalLM(Qwen2Config(
        vocab_size=128, hidden_size=64, num_layers=2, num_heads=4,
        num_kv_heads=2, intermediate_size=128,
        max_position_embeddings=64, rms_norm_eps=1e-6,
        rope_theta=10000.0, tensor_parallel=False))
    ours.eval()
    convert_hf_qwen2(ours, hf)
    ids = np.random.RandomState(0).randint(0, 128, (2, 16))
    with torch.no_grad():
        want = hf(torch.tensor(ids)).logits.numpy()
    got = np.asarray(ours(pt.to_tensor(ids))._array)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_qwen2_has_biases_llama_does_not():
    pt.seed(0)
    q = Qwen2ForCausalLM(Qwen2Config.from_preset("qwen2-tiny",
                                                 tensor_parallel=False))
    names = dict(q.named_parameters())
    assert "llama.layers.0.self_attn.q_proj.bias" in names
    assert "llama.layers.0.self_attn.o_proj.bias" not in names
    l = LlamaForCausalLM(LlamaConfig.from_preset("llama-tiny",
                                                 vocab_size=64,
                                                 tensor_parallel=False))
    assert "llama.layers.0.self_attn.q_proj.bias" not in dict(
        l.named_parameters())


class TestSlidingWindowKernel:
    def _qkv(self, L=96, B=2, H=4, Hkv=2, D=32, seed=0):
        rng = np.random.RandomState(seed)
        return (jnp.asarray(rng.randn(B, L, H, D), jnp.float32),
                jnp.asarray(rng.randn(B, L, Hkv, D), jnp.float32),
                jnp.asarray(rng.randn(B, L, Hkv, D), jnp.float32))

    @pytest.mark.parametrize("W", [16, 33, 96])
    def test_kernel_matches_banded_reference(self, W):
        from paddle_tpu.ops.pallas.flash_attention import flash_attention
        from paddle_tpu.ops.nn_kernels import sdpa_k
        q, k, v = self._qkv()
        want = sdpa_k(q, k, v, is_causal=True, sliding_window=W)
        got = flash_attention(q, k, v, is_causal=True, window=W,
                              block_q=32, block_k=32, interpret=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=2e-5)

        def g(fn):
            return jax.grad(lambda a, b, c: jnp.sum(fn(a, b, c) ** 2),
                            argnums=(0, 1, 2))(q, k, v)

        gw = g(lambda a, b, c: sdpa_k(a, b, c, is_causal=True,
                                      sliding_window=W))
        gg = g(lambda a, b, c: flash_attention(
            a, b, c, is_causal=True, window=W, block_q=32, block_k=32,
            interpret=True))
        for w_, g_ in zip(gw, gg):
            np.testing.assert_allclose(np.asarray(g_), np.asarray(w_),
                                       rtol=1e-4, atol=5e-5)

    def test_wide_window_equals_plain_causal(self):
        from paddle_tpu.ops.pallas.flash_attention import flash_attention
        q, k, v = self._qkv(L=64)
        full = flash_attention(q, k, v, is_causal=True, block_q=32,
                               block_k=32, interpret=True)
        wide = flash_attention(q, k, v, is_causal=True, window=500,
                               block_q=32, block_k=32, interpret=True)
        np.testing.assert_array_equal(np.asarray(full), np.asarray(wide))

    def test_window_requires_causal(self):
        from paddle_tpu.ops.pallas.flash_attention import flash_attention
        q, k, v = self._qkv(L=32)
        with pytest.raises(ValueError, match="is_causal"):
            flash_attention(q, k, v, window=8)


class TestSlidingWindowModel:
    def _model(self, W):
        pt.seed(4)
        return LlamaForCausalLM(LlamaConfig(
            vocab_size=64, hidden_size=32, num_layers=2, num_heads=4,
            num_kv_heads=2, intermediate_size=64,
            max_position_embeddings=64, tensor_parallel=False,
            sliding_window=W))

    def test_window_changes_long_context_only(self):
        pt.seed(4)
        base = self._model(None)
        pt.seed(4)
        swa = self._model(8)
        ids_short = pt.randint(0, 64, [1, 8])    # seq <= W: identical
        np.testing.assert_allclose(
            np.asarray(base(ids_short)._array),
            np.asarray(swa(ids_short)._array), rtol=1e-5, atol=1e-6)
        ids_long = pt.randint(0, 64, [1, 32])    # seq > W: band bites
        d = np.abs(np.asarray(base(ids_long)._array)
                   - np.asarray(swa(ids_long)._array)).max()
        assert d > 1e-3

    def test_decode_paths_agree_under_window(self):
        """Teacher-forced argmax == eager concat-cache generate ==
        jitted prealloc-cache generate, all with the window active —
        the three attention mask constructions must be one semantics."""
        from paddle_tpu.text.generation import generate
        from paddle_tpu.text.decode import jit_generate
        m = self._model(6)
        m.eval()
        ids = pt.to_tensor(np.array([[5, 17, 40, 3, 8, 9, 2, 33]],
                                    np.int64))
        NEW = 12
        jit_out = jit_generate(m, ids, max_new_tokens=NEW).numpy()
        eager_out = generate(m, ids, max_new_tokens=NEW).numpy()
        np.testing.assert_array_equal(jit_out, eager_out)
        # teacher-force: each generated token is the banded-argmax
        # continuation of its prefix
        logits = np.asarray(m(pt.to_tensor(
            jit_out.astype(np.int64)))._array)
        for t in range(8, 8 + NEW):
            assert int(logits[0, t - 1].argmax()) == int(jit_out[0, t]), t

    def test_swa_trains(self):
        import paddle_tpu.nn.functional as F
        m = self._model(8)
        opt = pt.optimizer.Adam(learning_rate=3e-3,
                                parameters=m.parameters())

        def loss_fn(mm, ids, labels):
            lg = mm(ids)
            return F.cross_entropy(
                lg.reshape([-1, 64]), labels.reshape([-1]),
                reduction="mean")

        step = pt.jit.train_step(m, loss_fn, opt)
        ids = pt.randint(0, 64, [4, 24])
        losses = [float(step(ids, ids)) for _ in range(12)]
        assert losses[-1] < losses[0], losses


def test_qwen2_generates_and_takes_lora():
    from paddle_tpu.text.generation import generate
    from paddle_tpu.text.peft import LoRAConfig, get_peft_model
    pt.seed(1)
    m = Qwen2ForCausalLM(Qwen2Config.from_preset("qwen2-tiny",
                                                 tensor_parallel=False))
    m.eval()
    ids = pt.randint(0, 256, [2, 6])
    out = generate(m, ids, max_new_tokens=5)
    assert tuple(out.shape) == (2, 11)
    lora = get_peft_model(m, LoRAConfig(
        r=2, target_modules=[".*q_proj", ".*v_proj"]))
    assert len(lora.replaced) == 4   # q+v per layer x 2 layers


def test_speculative_decode_agrees_under_window():
    """Batched speculative decoding on a sliding_window model routes
    through the PER-ROW-pos banded mask branch of
    _update_prealloc_cache — greedy output must still equal
    jit_generate exactly."""
    from paddle_tpu.text.decode import jit_generate, speculative_generate
    pt.seed(21)
    cfg = dict(vocab_size=64, hidden_size=32, num_layers=2, num_heads=4,
               num_kv_heads=2, intermediate_size=64,
               max_position_embeddings=64, tensor_parallel=False)
    tgt = LlamaForCausalLM(LlamaConfig(sliding_window=6, **cfg))
    tgt.eval()
    pt.seed(99)
    drf = LlamaForCausalLM(LlamaConfig(sliding_window=6, **cfg))
    drf.eval()
    ids = pt.to_tensor(np.array(
        [[5, 17, 40, 3, 8, 9, 2, 33], [1, 2, 3, 4, 5, 6, 7, 8]],
        np.int64))
    want = jit_generate(tgt, ids, max_new_tokens=10).numpy()
    got = speculative_generate(tgt, drf, ids, max_new_tokens=10,
                               num_speculative_tokens=3).numpy()
    np.testing.assert_array_equal(got, want)


def test_merged_training_forward_raises():
    from paddle_tpu.text.peft import LoRAConfig, get_peft_model
    pt.seed(3)
    m = Qwen2ForCausalLM(Qwen2Config.from_preset("qwen2-tiny",
                                                 tensor_parallel=False))
    lora = get_peft_model(m, LoRAConfig(r=2,
                                        target_modules=[".*q_proj"]))
    lora.eval()
    lora.merge()
    lora.train()
    with pytest.raises(RuntimeError, match="MERGED adapters"):
        lora(pt.randint(0, 256, [1, 4]))


def test_sliding_window_without_causal_raises():
    import paddle_tpu.nn.functional as F
    q = pt.randn([1, 8, 2, 16])
    with pytest.raises(ValueError, match="is_causal"):
        F.scaled_dot_product_attention(q, q, q, sliding_window=4)


def test_mistral_matches_transformers():
    """Mistral = llama weights + GQA + sliding window: loads through
    convert_hf_llama, and OUR banded attention must reproduce the HF
    Mistral forward when seq > window."""
    import torch
    from paddle_tpu.text.convert import convert_hf_llama
    from transformers import MistralConfig as HFC, \
        MistralForCausalLM as HFM

    torch.manual_seed(0)
    W = 8
    hf = HFM(HFC(vocab_size=128, hidden_size=64, intermediate_size=128,
                 num_hidden_layers=2, num_attention_heads=4,
                 num_key_value_heads=2, max_position_embeddings=64,
                 rope_theta=10000.0, rms_norm_eps=1e-6,
                 sliding_window=W, attention_dropout=0.0,
                 attn_implementation="eager")).eval()
    pt.seed(0)
    ours = LlamaForCausalLM(LlamaConfig(
        vocab_size=128, hidden_size=64, num_layers=2, num_heads=4,
        num_kv_heads=2, intermediate_size=128,
        max_position_embeddings=64, rms_norm_eps=1e-6,
        rope_theta=10000.0, tensor_parallel=False, sliding_window=W))
    ours.eval()
    convert_hf_llama(ours, hf)
    ids = np.random.RandomState(0).randint(0, 128, (2, 24))  # seq 3x W
    with torch.no_grad():
        want = hf(torch.tensor(ids)).logits.numpy()
    got = np.asarray(ours(pt.to_tensor(ids))._array)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)
