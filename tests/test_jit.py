"""jit.to_static / fused train step tests (SURVEY §4)."""
import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F


def test_to_static_matches_eager():
    m = nn.Sequential(nn.Linear(4, 8), nn.Tanh(), nn.Linear(8, 2))
    m.eval()
    x = pt.randn([3, 4])
    eager = m(x)
    static = pt.jit.to_static(m)
    out = static(x)
    np.testing.assert_allclose(out.numpy(), eager.numpy(), rtol=1e-5)


def test_to_static_backward():
    m = nn.Linear(4, 2)
    static = pt.jit.to_static(m)
    x = pt.randn([3, 4])
    loss = static(x).sum()
    loss.backward()
    assert m.weight.grad is not None
    # parity with eager grads
    wg = m.weight.grad.numpy().copy()
    m.clear_gradients()
    m(x).sum().backward()
    np.testing.assert_allclose(wg, m.weight.grad.numpy(), rtol=1e-5)


def test_to_static_buffer_update():
    bn = nn.BatchNorm1D(4)
    static = pt.jit.to_static(bn)
    bn.train()
    x = pt.randn([16, 4]) + 5.0
    static(x)
    assert bn._mean.numpy().mean() > 0.1  # running mean moved


def test_to_static_function():
    @pt.jit.to_static
    def f(a, b):
        return a * 2 + b

    x, y = pt.ones([3]), pt.ones([3])
    np.testing.assert_allclose(f(x, y).numpy(), [3, 3, 3])


def test_train_step_matches_eager():
    pt.seed(7)
    m1 = nn.Sequential(nn.Linear(4, 8), nn.Tanh(), nn.Linear(8, 1))
    m2 = nn.Sequential(nn.Linear(4, 8), nn.Tanh(), nn.Linear(8, 1))
    m2.set_state_dict(m1.state_dict())
    o1 = pt.optimizer.SGD(learning_rate=0.1, parameters=m1.parameters())
    o2 = pt.optimizer.SGD(learning_rate=0.1, parameters=m2.parameters())

    x = pt.randn([8, 4]); y = pt.randn([8, 1])

    def loss_fn(model, xi, yi):
        return F.mse_loss(model(xi), yi)

    step = pt.jit.train_step(m1, loss_fn, o1, donate=False)
    for _ in range(3):
        fused_loss = step(x, y)
        eager_loss = loss_fn(m2, x, y)
        eager_loss.backward()
        o2.step(); o2.clear_grad()
        np.testing.assert_allclose(float(fused_loss), float(eager_loss),
                                   rtol=1e-4)
    for (n1, p1), (n2, p2) in zip(m1.named_parameters(),
                                  m2.named_parameters()):
        np.testing.assert_allclose(p1.numpy(), p2.numpy(), rtol=1e-4,
                                   atol=1e-5)


def test_save_load(tmp_path):
    m = nn.Linear(4, 2)
    path = str(tmp_path / "model.pdparams")
    pt.jit.save(m.state_dict(), path)
    sd = pt.jit.load(path)
    m2 = nn.Linear(4, 2)
    m2.set_state_dict(sd)
    x = pt.randn([2, 4])
    np.testing.assert_allclose(m(x).numpy(), m2(x).numpy(), rtol=1e-6)


def test_recompute_matches_plain():
    from paddle_tpu.distributed import recompute
    pt.seed(3)
    block = nn.Sequential(nn.Linear(8, 16), nn.Tanh(), nn.Linear(16, 8))
    x = pt.randn([4, 8]); x.stop_gradient = False

    out_plain = block(x)
    out_plain.sum().backward()
    gx_plain = x.grad.numpy().copy()
    gw_plain = block[0].weight.grad.numpy().copy()

    x.clear_grad(); block.clear_gradients()
    out_rc = recompute(block, x)
    np.testing.assert_allclose(out_rc.numpy(), out_plain.numpy(), rtol=1e-5)
    out_rc.sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), gx_plain, rtol=1e-5)
    np.testing.assert_allclose(block[0].weight.grad.numpy(), gw_plain,
                               rtol=1e-5)
