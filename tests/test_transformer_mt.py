"""Seq2seq Transformer MT model (reference: the nn.Transformer MT
example): shapes, tiny overfit on a copy task, greedy decode."""
import numpy as np

import paddle_tpu as pt
from paddle_tpu.text import TransformerModel, transformer_mt_loss


def _tiny(weight_sharing=False):
    return TransformerModel(
        src_vocab_size=32, trg_vocab_size=32, max_length=32, d_model=32,
        n_head=4, num_encoder_layers=2, num_decoder_layers=2,
        d_inner_hid=64, dropout=0.0, weight_sharing=weight_sharing,
        bos_id=0, eos_id=1)


def test_forward_shapes_and_masking():
    pt.seed(0)
    m = _tiny()
    src = pt.randint(2, 32, [2, 7])
    trg = pt.randint(2, 32, [2, 5])
    logits = m(src, trg)
    assert logits.shape == [2, 5, 32]
    # pad masking changes the output
    src_np = src.numpy().copy()
    src_np[:, -2:] = 31  # pretend 31 is pad
    a = m(pt.to_tensor(src_np), trg, src_pad_id=31).numpy()
    b = m(pt.to_tensor(src_np), trg).numpy()
    assert not np.allclose(a, b)


def test_copy_task_overfit_and_greedy_decode():
    """Overfit src->src copying, then greedy decode reproduces it."""
    pt.seed(1)
    m = _tiny(weight_sharing=True)
    rng = np.random.RandomState(0)
    src = rng.randint(2, 30, (8, 6)).astype(np.int32)
    # target: bos + src + eos
    trg = np.concatenate(
        [np.zeros((8, 1), np.int32), src, np.ones((8, 1), np.int32)],
        axis=1)
    src_t, trg_t = pt.to_tensor(src), pt.to_tensor(trg)
    opt = pt.optimizer.Adam(learning_rate=3e-3,
                            parameters=m.parameters())
    step = pt.jit.train_step(
        m, lambda mm, s, t: transformer_mt_loss(mm, s, t,
                                                label_smooth_eps=0.0),
        opt)
    losses = [float(step(src_t, trg_t)) for _ in range(150)]
    assert losses[-1] < 0.15, (losses[0], losses[-1])
    m.eval()
    out = m.generate(src_t, max_length=8).numpy()
    # decoded tokens (after bos) reproduce the source for most positions
    acc = (out[:, 1:1 + src.shape[1]] == src).mean()
    assert acc > 0.95, acc


def test_cached_decode_matches_full_prefix():
    """Incremental KV-cache decode == naive full-prefix argmax decode."""
    pt.seed(3)
    m = _tiny()
    m.eval()
    src = pt.randint(2, 32, [2, 5])
    out = m.generate(src, max_length=6).numpy()

    # naive reference: re-run the decoder over the whole prefix each step
    from paddle_tpu import tensor_api as T
    memory = m.transformer.encoder(m._embed(m.src_embed, src))
    ref = np.zeros((2, 1), np.int32)
    cur = pt.to_tensor(ref)
    for _ in range(6):
        tgt_mask = m._causal_mask(cur.shape[1])
        dec = m.transformer.decoder(
            m._embed(m.trg_embed, cur), memory, tgt_mask, None)
        nxt = T.argmax(m.generator(dec[:, -1]), axis=-1).astype("int32")
        cur = T.concat([cur, nxt.unsqueeze(1)], axis=1)
    ref_np = cur.numpy()
    n = min(out.shape[1], ref_np.shape[1])
    # the two paths legitimately diverge after a row emits eos (generate
    # forces eos and may early-exit); compare only up to the first eos
    for row in range(out.shape[0]):
        eos_pos = np.where(out[row, :n] == m.eos_id)[0]
        upto = int(eos_pos[0]) + 1 if eos_pos.size else n
        np.testing.assert_array_equal(out[row, :upto],
                                      ref_np[row, :upto])


def test_generate_restores_train_mode_and_max_length_guard():
    import pytest
    pt.seed(4)
    m = _tiny()
    m.train()
    m.generate(pt.randint(2, 32, [1, 4]), max_length=3)
    assert m.training  # restored
    with pytest.raises(ValueError, match="max_length"):
        m(pt.randint(2, 32, [1, 40]), pt.randint(2, 32, [1, 4]))


def test_weight_sharing_requires_equal_vocabs():
    import pytest
    with pytest.raises(ValueError, match="equal src/trg"):
        TransformerModel(src_vocab_size=10, trg_vocab_size=12,
                         weight_sharing=True)
