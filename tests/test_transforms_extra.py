"""Round-2 vision transforms additions (reference:
python/paddle/vision/transforms/transforms.py)."""
import numpy as np

import paddle_tpu as pt
from paddle_tpu.vision import transforms as T


def _img(h=8, w=8, c=3, seed=0):
    return np.random.RandomState(seed).randint(
        0, 256, (h, w, c)).astype(np.uint8)


def test_pad_modes():
    img = _img()
    out = T.Pad(2)(img)
    assert out.shape == (12, 12, 3)
    assert (out[:2] == 0).all()
    out2 = T.Pad((1, 2), padding_mode="edge")(img)
    assert out2.shape == (12, 10, 3)


def test_grayscale():
    img = _img()
    g1 = T.Grayscale()(img)
    assert g1.shape == (8, 8, 1)
    g3 = T.Grayscale(3)(img)
    assert g3.shape == (8, 8, 3)
    np.testing.assert_array_equal(g3[..., 0], g3[..., 1])


def test_color_jitter_family():
    np.random.seed(0)
    img = _img()
    for t in (T.BrightnessTransform(0.4), T.ContrastTransform(0.4),
              T.SaturationTransform(0.4), T.HueTransform(0.2),
              T.ColorJitter(0.2, 0.2, 0.2, 0.1)):
        out = t(img)
        assert out.shape == img.shape and out.dtype == img.dtype
    # zero-strength: identity
    np.testing.assert_array_equal(T.BrightnessTransform(0.0)(img), img)


def test_random_rotation():
    np.random.seed(1)
    img = _img(16, 16)
    out = T.RandomRotation(30)(img)
    assert out.shape == (16, 16, 3)
    out2 = T.RandomRotation(90, expand=True)(img)
    assert out2.shape[2] == 3


def test_random_erasing():
    np.random.seed(2)
    img = np.ones((3, 16, 16), np.float32)
    out = T.RandomErasing(prob=1.0, value=0.0)(pt.to_tensor(img))
    assert float(out.numpy().min()) == 0.0   # some region erased
    kept = T.RandomErasing(prob=0.0)(img)
    np.testing.assert_array_equal(kept, img)


def test_native_imgproc_parity_and_fusion():
    """io/native/imgproc.cc fused uint8→normalized-CHW == the numpy
    ToTensor+Normalize pair; Compose auto-fuses the adjacent pair."""
    from paddle_tpu.io.native import imgproc
    mean, std = [0.485, 0.456, 0.406], [0.229, 0.224, 0.225]
    img = _img()
    if imgproc.available():
        got = imgproc.to_chw_f32(img, mean, std)
        want = (((img.astype(np.float32) / 255.0)
                 - np.asarray(mean, np.float32))
                / np.asarray(std, np.float32)).transpose(2, 0, 1)
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-6)
        gb = imgproc.to_chw_f32(np.stack([img] * 3), mean, std)
        np.testing.assert_allclose(gb[1], want, rtol=1e-4, atol=1e-6)
    pipe = T.Compose([T.ToTensor(), T.Normalize(mean, std)])
    assert len(pipe.transforms) == 1  # fused
    fused = pipe(img).numpy()
    unfused = T.Normalize(mean, std)(T.ToTensor()(img)).numpy()
    np.testing.assert_allclose(fused, unfused, rtol=1e-4, atol=1e-6)
    # float input falls back to the numpy pair inside the fused transform
    fimg = img.astype(np.float32) / 255.0
    np.testing.assert_allclose(
        pipe(fimg).numpy(),
        T.Normalize(mean, std)(T.ToTensor()(fimg)).numpy(),
        rtol=1e-5, atol=1e-6)


def test_compose_pipeline_with_new_transforms():
    np.random.seed(3)
    pipe = T.Compose([T.Pad(2), T.RandomRotation(10), T.Grayscale(3),
                      T.ToTensor()])
    out = pipe(_img())
    assert out.shape == [3, 12, 12]
