"""paddle_tpu.distributed.launch process runner.

Mirrors the reference's launch tests (test/legacy_test/test_launch_*.py):
env wiring, multi-process coordination via jax.distributed, elastic
restart, failure propagation.
"""
import os
import subprocess
import sys
import textwrap

import pytest

from paddle_tpu.distributed.launch import _parse_args, _worker_env, run

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_parse_and_env():
    args = _parse_args(["--nnodes", "2", "--node_rank", "1",
                        "--master", "10.0.0.1:1234",
                        "--nproc_per_node", "2", "train.py", "--lr", "0.1"])
    assert args.script == "train.py"
    assert args.script_args == ["--lr", "0.1"]
    env = _worker_env(args, 1)
    assert env["PT_COORDINATOR"] == "10.0.0.1:1234"
    assert env["PT_NUM_PROCESSES"] == "4"
    assert env["PT_PROCESS_ID"] == "3"
    assert env["PADDLE_TRAINER_ID"] == "3"


def test_two_process_coordination(tmp_path):
    script = tmp_path / "worker.py"
    script.write_text(textwrap.dedent(f"""
        import os, sys
        sys.path.insert(0, {REPO!r})
        import jax
        jax.config.update("jax_platforms", "cpu")
        import paddle_tpu.distributed as dist
        dist.init_parallel_env()
        out = os.path.join({str(tmp_path)!r},
                           f"rank{{dist.get_rank()}}.txt")
        with open(out, "w") as f:
            f.write(f"{{dist.get_rank()}}/{{dist.get_world_size()}}")
    """))
    code = run(["--nproc_per_node", "2", "--master", "127.0.0.1:18476",
                str(script)])
    assert code == 0
    assert (tmp_path / "rank0.txt").read_text() == "0/2"
    assert (tmp_path / "rank1.txt").read_text() == "1/2"


def test_elastic_restart(tmp_path):
    script = tmp_path / "flaky.py"
    marker = tmp_path / "ran_once"
    script.write_text(textwrap.dedent(f"""
        import os, sys
        m = {str(marker)!r}
        if not os.path.exists(m):
            open(m, "w").close()
            sys.exit(1)   # first attempt fails
    """))
    code = run(["--max_restarts", "1", str(script)])
    assert code == 0
    assert marker.exists()


def test_failure_propagates(tmp_path):
    script = tmp_path / "bad.py"
    script.write_text("import sys; sys.exit(3)")
    code = run([str(script)])
    assert code == 3
