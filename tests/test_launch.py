"""paddle_tpu.distributed.launch process runner.

Mirrors the reference's launch tests (test/legacy_test/test_launch_*.py):
env wiring, multi-process coordination via jax.distributed, elastic
restart, failure propagation.
"""
import os
import subprocess
import sys
import textwrap

import pytest

from paddle_tpu.distributed.launch import _parse_args, _worker_env, run

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_parse_and_env():
    args = _parse_args(["--nnodes", "2", "--node_rank", "1",
                        "--master", "10.0.0.1:1234",
                        "--nproc_per_node", "2", "train.py", "--lr", "0.1"])
    assert args.script == "train.py"
    assert args.script_args == ["--lr", "0.1"]
    env = _worker_env(args, 1)
    assert env["PT_COORDINATOR"] == "10.0.0.1:1234"
    assert env["PT_NUM_PROCESSES"] == "4"
    assert env["PT_PROCESS_ID"] == "3"
    assert env["PADDLE_TRAINER_ID"] == "3"


def test_two_process_coordination(tmp_path):
    script = tmp_path / "worker.py"
    script.write_text(textwrap.dedent(f"""
        import os, sys
        sys.path.insert(0, {REPO!r})
        import jax
        jax.config.update("jax_platforms", "cpu")
        import paddle_tpu.distributed as dist
        dist.init_parallel_env()
        out = os.path.join({str(tmp_path)!r},
                           f"rank{{dist.get_rank()}}.txt")
        with open(out, "w") as f:
            f.write(f"{{dist.get_rank()}}/{{dist.get_world_size()}}")
    """))
    code = run(["--nproc_per_node", "2", "--master", "127.0.0.1:18476",
                str(script)])
    assert code == 0
    assert (tmp_path / "rank0.txt").read_text() == "0/2"
    assert (tmp_path / "rank1.txt").read_text() == "1/2"


def test_elastic_restart(tmp_path):
    script = tmp_path / "flaky.py"
    marker = tmp_path / "ran_once"
    script.write_text(textwrap.dedent(f"""
        import os, sys
        m = {str(marker)!r}
        if not os.path.exists(m):
            open(m, "w").close()
            sys.exit(1)   # first attempt fails
    """))
    code = run(["--max_restarts", "1", str(script)])
    assert code == 0
    assert marker.exists()


def test_failure_propagates(tmp_path):
    script = tmp_path / "bad.py"
    script.write_text("import sys; sys.exit(3)")
    code = run([str(script)])
    assert code == 3


def test_two_process_dp_matches_single_process(tmp_path):
    """VERDICT #7: 2-process dp fleet training == single-process dp=2
    (same global batch, same seed), plus real cross-process eager
    collectives."""
    worker = tmp_path / "dp_worker.py"
    worker.write_text(textwrap.dedent(f"""
        import os, sys, json, re
        sys.path.insert(0, {REPO!r})
        # the pytest conftest's 8-virtual-device flag must not leak into
        # the workers: each process contributes exactly ONE device here
        os.environ["XLA_FLAGS"] = re.sub(
            r"--xla_force_host_platform_device_count=\\d+", "",
            os.environ.get("XLA_FLAGS", ""))
        import jax
        jax.config.update("jax_platforms", "cpu")
        import numpy as np
        import paddle_tpu as pt
        import paddle_tpu.nn as nn
        import paddle_tpu.nn.functional as F
        import paddle_tpu.distributed as dist
        from paddle_tpu.distributed import fleet

        dist.init_parallel_env()
        rank, world = dist.get_rank(), dist.get_world_size()
        assert world == 2, world

        # eager cross-process collectives
        t = pt.ones([2]) * float(rank + 1)
        dist.all_reduce(t)                      # 1 + 2 = 3
        np.testing.assert_allclose(t.numpy(), 3.0 * np.ones(2), rtol=1e-6)
        g = dist.all_gather([], pt.ones([1]) * float(rank))
        assert len(g) == 2

        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs = {{"dp_degree": 2, "mp_degree": 1,
                                    "pp_degree": 1}}
        fleet.init(is_collective=True, strategy=strategy)

        pt.seed(5)
        m = nn.Sequential(nn.Linear(8, 16), nn.Tanh(), nn.Linear(16, 8))
        opt = pt.optimizer.Adam(learning_rate=0.05,
                                parameters=m.parameters())
        step = fleet.build_train_step(
            m, lambda mm, x, y: F.mse_loss(mm(x), y), opt)

        pt.seed(7)
        x = pt.randn([8, 8]); y = pt.randn([8, 8])
        half = 4
        xl = x.numpy()[rank * half:(rank + 1) * half]
        yl = y.numpy()[rank * half:(rank + 1) * half]
        losses = [float(step(xl, yl)) for _ in range(3)]
        if rank == 0:
            with open(os.path.join({str(tmp_path)!r}, "losses.json"),
                      "w") as f:
                json.dump(losses, f)
    """))
    code = run(["--nproc_per_node", "2", "--master", "127.0.0.1:18991",
                str(worker)])
    assert code == 0
    import json
    mp_losses = json.loads((tmp_path / "losses.json").read_text())

    # single-process dp=2 reference on the virtual mesh
    import numpy as np
    import paddle_tpu as pt
    import paddle_tpu.nn as nn
    import paddle_tpu.nn.functional as F
    from paddle_tpu.distributed import fleet, mesh as mesh_mod
    prev = dict(mesh_mod._state)
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 1,
                               "pp_degree": 1}
    fleet.init(is_collective=True, strategy=strategy)
    pt.seed(5)
    m = nn.Sequential(nn.Linear(8, 16), nn.Tanh(), nn.Linear(16, 8))
    opt = pt.optimizer.Adam(learning_rate=0.05, parameters=m.parameters())
    step = fleet.build_train_step(
        m, lambda mm, x, y: F.mse_loss(mm(x), y), opt)
    pt.seed(7)
    x = pt.randn([8, 8]); y = pt.randn([8, 8])
    ref = [float(step(x, y)) for _ in range(3)]
    mesh_mod._state.update(prev)
    np.testing.assert_allclose(mp_losses, ref, rtol=1e-5)


def test_two_process_eager_send_recv(tmp_path):
    """VERDICT r3 item 10: eager paddle.distributed.send/recv between two
    launch processes (matched pair rides one process-mesh gather)."""
    import textwrap
    worker = tmp_path / "p2p_worker.py"
    worker.write_text(textwrap.dedent(f"""
        import os, sys
        sys.path.insert(0, {REPO!r})
        import jax
        jax.config.update("jax_platforms", "cpu")
        import numpy as np
        import paddle_tpu as pt
        import paddle_tpu.distributed as dist
        dist.init_parallel_env()
        rank = dist.get_rank()
        if rank == 0:
            x = pt.to_tensor(np.arange(6, dtype=np.float32).reshape(2, 3))
            dist.send(x, dst=1)
        else:
            y = pt.zeros([2, 3])
            dist.recv(y, src=0)
            np.testing.assert_allclose(
                y.numpy(), np.arange(6, dtype=np.float32).reshape(2, 3))
            with open(os.path.join({str(tmp_path)!r}, "ok.txt"), "w") as f:
                f.write("ok")
    """))
    code = run(["--nproc_per_node", "2", "--master", "127.0.0.1:18993",
                str(worker)])
    assert code == 0
    assert (tmp_path / "ok.txt").read_text() == "ok"


def test_single_process_send_recv_loopback():
    """world=1 self-send loops through the in-process queue."""
    import numpy as np
    import paddle_tpu as pt
    import paddle_tpu.distributed as dist
    x = pt.to_tensor(np.ones((3,), np.float32) * 7)
    dist.send(x, dst=0)
    y = pt.zeros([3])
    dist.recv(y, src=0)
    np.testing.assert_allclose(y.numpy(), 7.0)
