"""Jitted decode loop (one XLA program) vs the eager KV-cache path.

Mirrors the reference's generation tests: greedy equality vs eager,
sampling shapes, eos handling, LLaMA GQA decode.
"""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.text import (GPTConfig, GPTForCausalLM, LlamaConfig,
                             LlamaForCausalLM)


def _tiny_gpt():
    pt.seed(0)
    cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=2, num_heads=4,
                    max_position_embeddings=64, hidden_dropout=0.0,
                    attention_dropout=0.0, tensor_parallel=False)
    return GPTForCausalLM(cfg)


def _tiny_llama():
    pt.seed(0)
    cfg = LlamaConfig(vocab_size=64, hidden_size=32, num_layers=2,
                      num_heads=4, num_kv_heads=2, intermediate_size=64,
                      max_position_embeddings=64, tensor_parallel=False)
    return LlamaForCausalLM(cfg)


def test_jit_greedy_matches_eager_gpt():
    m = _tiny_gpt()
    ids = pt.randint(0, 64, [2, 5])
    eager = m.generate(ids, max_new_tokens=6, use_jit=False)
    jit = m.generate(ids, max_new_tokens=6, use_jit=True)
    np.testing.assert_array_equal(jit.numpy(), eager.numpy())


def test_jit_greedy_matches_eager_llama():
    m = _tiny_llama()
    ids = pt.randint(0, 64, [2, 4])
    eager = m.generate(ids, max_new_tokens=5, use_jit=False)
    jit = m.generate(ids, max_new_tokens=5, use_jit=True)
    np.testing.assert_array_equal(jit.numpy(), eager.numpy())


def test_jit_sampling_shapes_and_cache_reuse():
    m = _tiny_gpt()
    ids = pt.randint(0, 64, [2, 4])
    out = m.generate(ids, max_new_tokens=5, do_sample=True, top_k=10,
                     top_p=0.9, temperature=0.8)
    assert out.shape == [2, 9]
    # second call hits the compiled-fn cache (same static config)
    out2 = m.generate(ids, max_new_tokens=5, do_sample=True, top_k=10,
                      top_p=0.9, temperature=0.8)
    assert out2.shape == [2, 9]
    assert len(m._jit_decode_cache) == 1


def test_jit_eos_padding():
    m = _tiny_gpt()
    ids = pt.randint(0, 64, [1, 4])
    # find what greedy emits first, then use it as "eos" so decoding stops
    first = m.generate(ids, max_new_tokens=1, use_jit=False).numpy()[0, -1]
    out = m.generate(ids, max_new_tokens=6, eos_token_id=int(first)).numpy()
    # every generated position after (and including) the eos must be eos
    assert (out[0, 4:] == first).all()


def test_prealloc_cache_matches_full_forward():
    m = _tiny_gpt()
    m.eval()
    ids = pt.randint(0, 64, [1, 6])
    full_logits = m(ids)
    caches = m.new_caches(1, max_length=6)
    with pt.no_grad():
        pre_logits = m(ids, caches=caches)
    np.testing.assert_allclose(pre_logits.numpy(), full_logits.numpy(),
                               rtol=2e-4, atol=2e-5)


class TestJitBeamSearch:
    """decode.jit_beam_search: the whole beam loop (prefill + reorder +
    cache gathers) as ONE compiled program, token-exact vs the eager
    generation.beam_search reference."""

    def _model(self):
        pt.seed(11)
        cfg = GPTConfig(vocab_size=96, hidden_size=48, num_layers=3,
                        num_heads=4, max_position_embeddings=96,
                        hidden_dropout=0.0, attention_dropout=0.0,
                        tensor_parallel=False)
        m = GPTForCausalLM(cfg)
        m.eval()
        return m

    def test_matches_eager_no_eos(self):
        from paddle_tpu.text.generation import beam_search
        from paddle_tpu.text.decode import jit_beam_search
        m = self._model()
        ids = pt.to_tensor(np.array([[5, 17, 40, 3], [1, 2, 3, 4]],
                                    np.int64))
        want = beam_search(m, ids, beam_size=4, max_new_tokens=10,
                           length_penalty=0.8).numpy()
        got = jit_beam_search(m, ids, beam_size=4, max_new_tokens=10,
                              length_penalty=0.8).numpy()
        np.testing.assert_array_equal(got, want)

    def test_matches_eager_with_eos(self):
        from paddle_tpu.text.generation import beam_search
        from paddle_tpu.text.decode import jit_beam_search
        m = self._model()
        ids = pt.to_tensor(np.array([[5, 17, 40, 3], [1, 2, 3, 4]],
                                    np.int64))
        plain = beam_search(m, ids, beam_size=3, max_new_tokens=12).numpy()
        eos = int(plain[0, 4 + 2])       # a token a beam REALLY emits
        want = beam_search(m, ids, beam_size=3, max_new_tokens=12,
                           eos_token_id=eos).numpy()
        got = jit_beam_search(m, ids, beam_size=3, max_new_tokens=12,
                              eos_token_id=eos).numpy()
        L = want.shape[1]
        np.testing.assert_array_equal(got[:, :L], want)
        # jitted buffer is fixed-length: the tail after the eager early
        # exit is eos padding (frozen-beam continuations)
        if got.shape[1] > L:
            assert (got[:, L:] == eos).all()


def test_generate_routes_num_beams():
    from paddle_tpu.text.generation import generate, beam_search
    pt.seed(11)
    cfg = GPTConfig(vocab_size=96, hidden_size=48, num_layers=2,
                    num_heads=4, max_position_embeddings=64,
                    hidden_dropout=0.0, attention_dropout=0.0,
                    tensor_parallel=False)
    m = GPTForCausalLM(cfg)
    m.eval()
    ids = pt.to_tensor(np.array([[5, 17, 40, 3]], np.int64))
    want = beam_search(m, ids, beam_size=3, max_new_tokens=6).numpy()
    got = generate(m, ids, max_new_tokens=6, num_beams=3).numpy()
    np.testing.assert_array_equal(got, want)
    import pytest as _pt
    with _pt.raises(NotImplementedError, match="compose"):
        generate(m, ids, num_beams=2, do_sample=True)


def test_moe_gpt_decodes_through_jitted_paths():
    """MoE blocks (GShard static-capacity dispatch) compose with the
    preallocated-cache decode loop AND the jitted beam search —
    greedy jit decode is token-exact vs the eager loop."""
    from paddle_tpu.text.generation import generate
    from paddle_tpu.text.decode import jit_beam_search, jit_generate
    pt.seed(5)
    cfg = GPTConfig(vocab_size=96, hidden_size=48, num_layers=2,
                    num_heads=4, max_position_embeddings=64,
                    hidden_dropout=0.0, attention_dropout=0.0,
                    tensor_parallel=False, num_experts=4, moe_top_k=2)
    m = GPTForCausalLM(cfg)
    m.eval()
    ids = pt.to_tensor(np.array([[5, 17, 40, 3], [9, 8, 7, 6]], np.int64))
    eager = generate(m, ids, max_new_tokens=8).numpy()
    jit = jit_generate(m, ids, max_new_tokens=8).numpy()
    np.testing.assert_array_equal(eager, jit)
    beam = jit_beam_search(m, ids, beam_size=3, max_new_tokens=6)
    assert tuple(beam.shape) == (2, 10)
