"""Round-2 linalg/optimizer/sampler additions vs numpy/scipy/torch."""
import numpy as np
import pytest
import torch

import paddle_tpu as pt
from paddle_tpu import linalg as L


def _spd(n, seed=0):
    rng = np.random.RandomState(seed)
    a = rng.randn(n, n).astype(np.float32)
    return a @ a.T + n * np.eye(n, dtype=np.float32)


def test_lu_roundtrip():
    rng = np.random.RandomState(0)
    a = rng.randn(5, 5).astype(np.float32)
    lu_packed, piv = L.lu(pt.to_tensor(a))
    P, Lm, U = L.lu_unpack(lu_packed, piv)
    recon = P.numpy() @ Lm.numpy() @ U.numpy()
    np.testing.assert_allclose(recon, a, rtol=1e-4, atol=1e-4)


def test_cholesky_solve():
    a = _spd(4)
    b = np.random.RandomState(1).randn(4, 2).astype(np.float32)
    c = np.linalg.cholesky(a).astype(np.float32)
    got = L.cholesky_solve(pt.to_tensor(b), pt.to_tensor(c)).numpy()
    np.testing.assert_allclose(a @ got, b, rtol=1e-3, atol=1e-3)


def test_matrix_exp():
    a = np.random.RandomState(2).randn(4, 4).astype(np.float32) * 0.3
    got = L.matrix_exp(pt.to_tensor(a)).numpy()
    want = torch.matrix_exp(torch.tensor(a)).numpy()
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def test_cond_and_eig():
    a = _spd(4, seed=3)
    got = float(L.cond(pt.to_tensor(a)))
    want = float(np.linalg.cond(a))
    assert abs(got - want) / want < 1e-3
    w, v = L.eig(pt.to_tensor(a))
    wn = np.sort(np.real(w.numpy()))
    np.testing.assert_allclose(wn, np.sort(np.linalg.eigvalsh(a)),
                               rtol=1e-3)


def test_cov_corrcoef():
    x = np.random.RandomState(4).randn(3, 50).astype(np.float32)
    np.testing.assert_allclose(L.cov(pt.to_tensor(x)).numpy(),
                               np.cov(x), rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(L.corrcoef(pt.to_tensor(x)).numpy(),
                               np.corrcoef(x), rtol=1e-4, atol=1e-5)


def test_householder_product_reconstructs_q():
    a = np.random.RandomState(5).randn(6, 4).astype(np.float32)
    import scipy.linalg as sl
    h, tau = sl.qr(a, mode="raw")[0]   # LAPACK geqrf output
    q = L.householder_product(pt.to_tensor(np.ascontiguousarray(h)),
                              pt.to_tensor(np.ascontiguousarray(tau)))
    q_want, _ = np.linalg.qr(a)
    np.testing.assert_allclose(np.abs(q.numpy()[:, :4]), np.abs(q_want),
                               rtol=1e-3, atol=1e-3)


@pytest.mark.parametrize("cls", ["NAdam", "RAdam", "ASGD", "Rprop"])
def test_new_optimizers_converge_on_quadratic(cls):
    pt.seed(0)
    w = pt.to_tensor(np.array([3.0, -2.0], np.float32))
    w.stop_gradient = False
    opt = getattr(pt.optimizer, cls)(learning_rate=0.1, parameters=[w])
    for _ in range(150):
        loss = (w ** 2).sum()
        loss.backward()
        opt.step()
        opt.clear_grad()
    assert float((w ** 2).sum()) < 1e-2, (cls, w.numpy())


def test_nadam_radam_match_torch_few_steps():
    for name, tcls in [("NAdam", torch.optim.NAdam),
                       ("RAdam", torch.optim.RAdam)]:
        w0 = np.array([1.0, -2.0, 0.5], np.float32)
        wp = pt.to_tensor(w0.copy()); wp.stop_gradient = False
        wt = torch.tensor(w0.copy(), requires_grad=True)
        po = getattr(pt.optimizer, name)(learning_rate=0.01,
                                         parameters=[wp])
        to = tcls([wt], lr=0.01)
        for _ in range(5):
            lp = (wp ** 2).sum(); lp.backward(); po.step(); po.clear_grad()
            to.zero_grad(); lt = (wt ** 2).sum(); lt.backward(); to.step()
        np.testing.assert_allclose(wp.numpy(), wt.detach().numpy(),
                                   rtol=2e-3, atol=2e-4), name


def test_weighted_and_subset_samplers():
    from paddle_tpu.io import WeightedRandomSampler, SubsetRandomSampler
    np.random.seed(0)
    s = WeightedRandomSampler([0.0, 0.0, 1.0, 1.0], num_samples=200)
    idx = list(s)
    assert len(idx) == 200 and set(idx) <= {2, 3}
    sub = SubsetRandomSampler([5, 7, 9])
    out = list(sub)
    assert sorted(out) == [5, 7, 9]
    with pytest.raises(ValueError):
        WeightedRandomSampler([-1.0, 2.0], 2)
