"""MoE / expert parallelism (SURVEY §2 distributed; reference analog:
paddle.incubate.distributed.models.moe): routing math, dense parity,
capacity drop, ep-sharded fleet step == unsharded eager step."""
import numpy as np
import pytest
import jax
import jax.numpy as jnp

import paddle_tpu as pt
import paddle_tpu.nn as nn
from paddle_tpu.distributed import fleet, mesh as mesh_mod
from paddle_tpu.incubate.nn import (FusedFeedForward, FusedMultiHeadAttention,
                                    MoELayer, moe_aux_loss)


def test_moe_forward_backward():
    pt.seed(0)
    m = MoELayer(16, 32, num_experts=4, top_k=2)
    x = pt.randn([2, 8, 16])
    y = m(x)
    assert y.shape == [2, 8, 16]
    assert np.isfinite(float(m.aux_loss))
    loss = y.mean() + 0.01 * moe_aux_loss(m)
    loss.backward()
    assert np.abs(m.gate_weight.grad.numpy()).sum() > 0
    assert np.abs(m.w1.grad.numpy()).sum() > 0
    assert np.abs(m.w2.grad.numpy()).sum() > 0


def test_moe_dense_parity():
    """top_k == num_experts with ample capacity == softmax-weighted dense
    mixture of the expert FFNs."""
    pt.seed(1)
    m = MoELayer(8, 16, num_experts=2, top_k=2, capacity_factor=100.0)
    x = pt.randn([4, 8])
    y = m(x)
    xa = x.numpy()
    logits = xa @ m.gate_weight.numpy()
    e = np.exp(logits - logits.max(-1, keepdims=True))
    probs = e / e.sum(-1, keepdims=True)
    ref = np.zeros_like(xa)
    for k in range(2):
        h = np.asarray(jax.nn.gelu(
            jnp.asarray(xa @ m.w1.numpy()[k] + m.b1.numpy()[k]),
            approximate=True))
        ref += probs[:, k:k + 1] * (h @ m.w2.numpy()[k] + m.b2.numpy()[k])
    np.testing.assert_allclose(y.numpy(), ref, rtol=2e-4, atol=2e-5)


def test_moe_capacity_drop():
    """With capacity 1 slot per expert, overflow tokens get zero output
    (their combine weights vanish — residual path carries them)."""
    pt.seed(2)
    m = MoELayer(8, 16, num_experts=2, top_k=1, capacity_factor=1e-9)
    m.eval()  # eval_capacity_factor also tiny via monkeypatch below
    m.eval_capacity_factor = 1e-9
    x = pt.randn([6, 8])
    y = m(x)
    # capacity floor is 1 → at most 2 tokens (one per expert) are routed
    nonzero_rows = (np.abs(y.numpy()) > 1e-9).any(axis=1).sum()
    assert nonzero_rows <= 2


def test_moe_aux_loss_balanced_lower_bound():
    """Load-balancing loss is minimized (=1) under a uniform router; a
    random router must be >= 1 - eps."""
    pt.seed(3)
    m = MoELayer(8, 8, num_experts=4, top_k=2)
    m(pt.randn([64, 8]))
    assert float(m.aux_loss) >= 0.99


def test_expert_choice_gate():
    """Expert-choice: E=1 with full capacity equals the single expert's
    dense FFN (softmax over 1 expert == weight 1); E>1 is balanced by
    construction (every expert processes exactly C tokens)."""
    pt.seed(4)
    m = MoELayer(8, 16, num_experts=1, top_k=1, gate="expert_choice",
                 capacity_factor=1.0)
    x = pt.randn([6, 8])
    y = m(x)
    xa = x.numpy()
    h = np.asarray(jax.nn.gelu(
        jnp.asarray(xa @ m.w1.numpy()[0] + m.b1.numpy()[0]),
        approximate=True))
    ref = h @ m.w2.numpy()[0] + m.b2.numpy()[0]
    np.testing.assert_allclose(y.numpy(), ref, rtol=2e-4, atol=2e-5)
    assert float(m.aux_loss) == 0.0  # no aux loss needed

    m2 = MoELayer(8, 16, num_experts=4, top_k=1, gate="expert_choice",
                  capacity_factor=1.0)
    x2 = pt.randn([16, 8])
    x2.stop_gradient = False
    y2 = m2(x2)
    assert y2.shape == [16, 8]
    y2.mean().backward()
    assert np.abs(m2.gate_weight.grad.numpy()).sum() > 0
    assert np.abs(x2.grad.numpy()).sum() > 0

    with pytest.raises(ValueError, match="gate"):
        MoELayer(8, 16, num_experts=2, gate="bogus")


@pytest.fixture
def _restore_mesh():
    prev = dict(mesh_mod._state)
    yield
    mesh_mod._state.update(prev)


class _MoENet(nn.Layer):
    def __init__(self, d=16, f=32, experts=4):
        super().__init__()
        self.inp = nn.Linear(d, d)
        self.moe = MoELayer(d, f, num_experts=experts, top_k=2,
                            capacity_factor=2.0)
        self.out = nn.Linear(d, 1)

    def forward(self, x):
        return self.out(x + self.moe(self.inp(x)))


def _moe_loss(model, x, y):
    pred = model(x)
    loss = ((pred - y) ** 2).mean()
    aux = moe_aux_loss(model)
    return loss + 0.01 * aux if aux is not None else loss


def test_moe_ep_fleet_matches_eager(_restore_mesh):
    """ep-sharded fleet train step == unsharded eager step (the tp==dense /
    zero==unsharded pattern, for the expert axis)."""
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 1, "pp_degree": 1,
                               "ep_degree": 4}
    fleet.init(is_collective=True, strategy=strategy)
    assert mesh_mod.degree("ep") == 4

    pt.seed(5)
    m1 = _MoENet()
    assert m1.moe.w1.pspec == jax.sharding.PartitionSpec("ep", None, None)
    m2 = _MoENet()
    m2.set_state_dict(m1.state_dict())
    x = pt.randn([8, 16])
    y = pt.randn([8, 1])

    o1 = pt.optimizer.Adam(learning_rate=0.05, parameters=m1.parameters())
    step = fleet.build_train_step(m1, _moe_loss, o1)
    o2 = pt.optimizer.Adam(learning_rate=0.05, parameters=m2.parameters())

    for _ in range(3):
        dist_loss = step(x, y)
        ref_loss = _moe_loss(m2, x, y)
        ref_loss.backward()
        o2.step(); o2.clear_grad()
        np.testing.assert_allclose(float(dist_loss), float(ref_loss),
                                   rtol=2e-4, atol=2e-5)


def test_moe_gpt_ep_zero_recompute_integration(_restore_mesh):
    """The full hybrid story in one step: MoE GPT under dp x ep with
    ZeRO-2 state sharding and recompute — loss matches the same model
    trained unsharded."""
    from paddle_tpu.text.gpt import GPTConfig, GPTForCausalLM, gpt_loss_fn
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 1,
                               "pp_degree": 1, "ep_degree": 2,
                               "sharding_degree": 2, "sharding_stage": 2}
    fleet.init(is_collective=True, strategy=strategy)

    def build(use_recompute):
        pt.seed(7)
        cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=2,
                        num_heads=4, max_position_embeddings=32,
                        hidden_dropout=0.0, attention_dropout=0.0,
                        tensor_parallel=False, num_experts=2, moe_top_k=1,
                        use_recompute=use_recompute)
        return GPTForCausalLM(cfg)

    # reference runs WITHOUT recompute: an independent baseline, so a bug
    # in the aux-across-checkpoint path cannot cancel out on both sides
    m1, m2 = build(True), build(False)
    m2.set_state_dict(m1.state_dict())
    ids = pt.randint(0, 64, [4, 8])
    labels = pt.randint(0, 64, [4, 8])
    o1 = pt.optimizer.Adam(learning_rate=0.01, parameters=m1.parameters())
    step = fleet.build_train_step(m1, gpt_loss_fn, o1)
    o2 = pt.optimizer.Adam(learning_rate=0.01, parameters=m2.parameters())
    for _ in range(2):
        dist_loss = step(ids, labels)
        ref_loss = gpt_loss_fn(m2, ids, labels)
        ref_loss.backward()
        o2.step(); o2.clear_grad()
        np.testing.assert_allclose(float(dist_loss), float(ref_loss),
                                   rtol=2e-4, atol=2e-5)


def test_mesh_ep_axis(_restore_mesh):
    m = mesh_mod.build_mesh(dp=2, pp=1, mp=2, ep=2)
    assert m.shape == {"dp": 2, "pp": 1, "mp": 2, "ep": 2}
    assert mesh_mod.degree("ep") == 2
    # ep defaults to 1 and stays off the mesh for compatibility
    m3 = mesh_mod.build_mesh(dp=2, pp=2, mp=2)
    assert "ep" not in m3.axis_names


def test_gpt_moe_forward():
    from paddle_tpu.text.gpt import GPTConfig, GPTForCausalLM, gpt_loss_fn
    cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=2, num_heads=4,
                    max_position_embeddings=32, num_experts=2, moe_top_k=1)
    model = GPTForCausalLM(cfg)
    ids = pt.randint(0, 64, [2, 8])
    logits = model(ids)
    assert logits.shape == [2, 8, 64]
    labels = pt.randint(0, 64, [2, 8])
    loss = gpt_loss_fn(model, ids, labels)
    loss.backward()
    moe_block = model.gpt.h[1].mlp
    assert isinstance(moe_block, MoELayer) or \
        any(isinstance(s, MoELayer) for s in moe_block.sublayers())
    aux = moe_aux_loss(model)
    assert aux is not None and np.isfinite(float(aux))


def test_fused_attention_and_ffn():
    pt.seed(7)
    attn = FusedMultiHeadAttention(32, 4, dropout_rate=0.0,
                                   attn_dropout_rate=0.0)
    x = pt.randn([2, 6, 32])
    y = attn(x)
    assert y.shape == [2, 6, 32]
    ffn = FusedFeedForward(32, 64, dropout_rate=0.0, activation="gelu",
                           normalize_before=True)
    z = ffn(y)
    assert z.shape == [2, 6, 32]
    loss = z.mean()
    loss.backward()
    assert np.abs(attn.qkv_weight.grad.numpy()).sum() > 0
    assert np.abs(ffn.linear1_weight.grad.numpy()).sum() > 0
