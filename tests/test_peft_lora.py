"""LoRA fine-tuning (text/peft.py; reference analog: paddlenlp.peft).

Pinned: zero-init exactness at step 0, frozen-base training through the
fused step (base weights bit-identical after training, adapters moved),
merge/unmerge exactness, adapter-only save/load round-trip, and helper
delegation (generate through the wrapper).
"""
import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.nn.functional as F
from paddle_tpu.text import GPTConfig, GPTForCausalLM, gpt_loss_fn
from paddle_tpu.text.peft import (LoRAConfig, LoRAModel, LoRALinear,
                                  get_peft_model)


def _gpt(seed=0):
    pt.seed(seed)
    cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=2,
                    num_heads=4, max_position_embeddings=32,
                    hidden_dropout=0.0, attention_dropout=0.0,
                    tensor_parallel=False)
    return GPTForCausalLM(cfg)


def _snapshot(model, key):
    return {n: np.asarray(p._array).copy()
            for n, p in model.named_parameters() if key(n)}


class TestLoRA:
    def test_zero_init_is_identity(self):
        base = _gpt()
        ids = pt.randint(0, 64, [2, 8])
        want = base(ids).numpy()
        lora = get_peft_model(base, LoRAConfig(r=4))
        got = lora(ids).numpy()
        np.testing.assert_array_equal(got, want)   # B starts at zero
        assert len(lora.replaced) == 2             # qkv_proj per layer

    def test_trainable_surface_is_adapters_only(self):
        lora = LoRAModel(_gpt(), LoRAConfig(
            r=4, target_modules=[".*qkv_proj", ".*out_proj"]))
        train = lora.trainable_parameters()
        total = list(lora.model.parameters())
        n_train = sum(p.size for p in train)
        n_total = sum(p.size for p in total)
        # toy dims make the ratio generous; at real width it is ~0.1%
        assert n_train < 0.10 * n_total
        names = dict(lora.model.named_parameters())
        for n, p in names.items():
            is_adapter = "lora_" in n
            assert p.stop_gradient != is_adapter, n

    def test_fused_step_trains_adapters_freezes_base(self):
        lora = LoRAModel(_gpt(3), LoRAConfig(r=4, lora_alpha=8))
        base_before = _snapshot(lora.model, lambda n: "lora_" not in n)
        opt = pt.optimizer.AdamW(learning_rate=3e-2,
                                 parameters=lora.trainable_parameters())
        step = pt.jit.train_step(lora, gpt_loss_fn, opt)
        ids = pt.randint(0, 64, [4, 16])
        labels = pt.randint(0, 64, [4, 16])
        losses = [float(step(ids, labels)) for _ in range(25)]
        assert losses[-1] < losses[0] - 0.3, losses
        base_after = _snapshot(lora.model, lambda n: "lora_" not in n)
        for n in base_before:   # frozen: BIT-identical through the step
            np.testing.assert_array_equal(base_before[n], base_after[n],
                                          err_msg=n)
        ad = _snapshot(lora.model, lambda n: "lora_B" in n)
        assert any(np.abs(v).sum() > 0 for v in ad.values())

    def test_merge_unmerge_exact(self):
        lora = LoRAModel(_gpt(5), LoRAConfig(r=4))
        # give the adapters nonzero weights
        for n, p in lora.adapter_state_dict().items():
            pt.seed(hash(n) % 1000)
            p._inplace_assign(0.02 * pt.randn(list(p.shape))._array)
        ids = pt.randint(0, 64, [2, 8])
        want = lora(ids).numpy()
        w0 = _snapshot(lora.model, lambda n: n.endswith("base.weight"))
        # merge() refuses in train mode (a compiled step would
        # double-count the adapter) — that guard is part of the contract
        with pytest.raises(RuntimeError, match="train mode"):
            lora.merge()
        lora.eval()
        lora.merge()
        got = lora(ids).numpy()
        np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)
        lora.unmerge()
        np.testing.assert_allclose(lora(ids).numpy(), want, rtol=2e-5,
                                   atol=2e-5)
        w1 = _snapshot(lora.model, lambda n: n.endswith("base.weight"))
        for n in w0:
            np.testing.assert_allclose(w0[n], w1[n], rtol=1e-5,
                                       atol=1e-6, err_msg=n)

    def test_adapter_save_load_roundtrip(self, tmp_path):
        lora = LoRAModel(_gpt(7), LoRAConfig(r=2))
        for n, p in lora.adapter_state_dict().items():
            pt.seed(hash(n) % 997)
            p._inplace_assign(0.05 * pt.randn(list(p.shape))._array)
        ids = pt.randint(0, 64, [2, 8])
        want = lora(ids).numpy()
        path = str(tmp_path / "adapter")
        lora.save_adapter(path)
        fresh = LoRAModel(_gpt(7), LoRAConfig(r=2))
        assert not np.allclose(fresh(ids).numpy(), want)
        fresh.load_adapter(path)
        np.testing.assert_allclose(fresh(ids).numpy(), want, rtol=1e-5,
                                   atol=1e-6)

    def test_generate_delegates_through_wrapper(self):
        from paddle_tpu.text.generation import generate
        lora = LoRAModel(_gpt(9), LoRAConfig(r=2))
        lora.eval()
        ids = pt.randint(0, 64, [1, 6])
        out = generate(lora, ids, max_new_tokens=4)
        assert tuple(out.shape) == (1, 10)

    def test_no_match_raises(self):
        with pytest.raises(ValueError, match="no Linear matched"):
            LoRAModel(_gpt(), LoRAConfig(target_modules=["nope.*"]))

    def test_wrap_non_linear_raises(self):
        with pytest.raises(TypeError, match="wraps nn.Linear"):
            LoRALinear(pt.nn.LayerNorm(8), 4, 8)


def test_frozen_params_get_no_optimizer_state():
    """The fused step must not allocate moments/master for frozen base
    weights — a LoRA fine-tune's optimizer HBM is adapter-sized."""
    lora = LoRAModel(_gpt(11), LoRAConfig(r=2))
    opt = pt.optimizer.AdamW(learning_rate=1e-3,
                             parameters=lora.trainable_parameters())
    step = pt.jit.train_step(lora, gpt_loss_fn, opt)
    ids = pt.randint(0, 64, [2, 8])
    float(step(ids, ids))
    names = [n for n, _ in lora.named_parameters()]
    state = step._opt_state
    assert len(state) == len(names)
    for n, slots in zip(names, state):
        if "lora_" in n:
            assert slots, n                    # adapters: real moments
        else:
            assert slots == {}, n              # frozen: zero HBM


def test_lora_under_fleet_dp_zero2():
    """LoRA composes with the hybrid engine: dp8 + ZeRO-2 on the virtual
    mesh, base weights bit-frozen, optimizer slots EMPTY for the frozen
    base (fleet init_state takes the frozen mask too)."""
    from paddle_tpu.distributed import fleet
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 8, "mp_degree": 1,
                               "pp_degree": 1, "sharding_degree": 8,
                               "sharding_stage": 2}
    fleet.init(is_collective=True, strategy=strategy)
    lora = LoRAModel(_gpt(31), LoRAConfig(r=4))
    opt = pt.optimizer.AdamW(learning_rate=1e-2,
                             parameters=lora.trainable_parameters())
    step = fleet.build_train_step(lora, gpt_loss_fn, opt)
    ids = pt.randint(0, 64, [8, 16])
    before = _snapshot(lora.model, lambda n: "lora_" not in n)
    losses = [float(step(ids, ids)) for _ in range(6)]
    assert losses[-1] < losses[0], losses
    after = _snapshot(lora.model, lambda n: "lora_" not in n)
    for n in before:
        np.testing.assert_array_equal(before[n], after[n], err_msg=n)
    empt = sum(1 for s in step._opt_state if s == {})
    assert 0 < empt < len(step._opt_state)


def test_lora_wraps_tensor_parallel_linears():
    """Column/RowParallelLinear projections wrap too: the adapters carry
    Megatron-matching shardings (B col-sharded / A row-sharded) and
    train under dp x mp with the base bit-frozen."""
    from paddle_tpu.distributed import fleet
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 4, "mp_degree": 2,
                               "pp_degree": 1}
    fleet.init(is_collective=True, strategy=strategy)
    pt.seed(0)
    cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=2,
                    num_heads=4, max_position_embeddings=32,
                    hidden_dropout=0.0, attention_dropout=0.0,
                    tensor_parallel=True)
    lora = LoRAModel(GPTForCausalLM(cfg), LoRAConfig(
        r=4, target_modules=[".*qkv_proj", ".*out_proj"]))
    assert len(lora.replaced) == 4
    # adapter shardings follow the base split
    subs = {p: s for p, s in lora.model.named_sublayers()
            if isinstance(s, LoRALinear)}
    qkv = subs["gpt.h.0.attn.qkv_proj"]
    out = subs["gpt.h.0.attn.out_proj"]
    assert tuple(qkv.lora_B.pspec) == (None, "mp")
    assert tuple(out.lora_A.pspec) == ("mp", None)
    opt = pt.optimizer.AdamW(learning_rate=1e-2,
                             parameters=lora.trainable_parameters())
    step = fleet.build_train_step(lora, gpt_loss_fn, opt)
    ids = pt.randint(0, 64, [8, 16])
    before = _snapshot(lora.model, lambda n: "lora_" not in n)
    losses = [float(step(ids, ids)) for _ in range(6)]
    assert losses[-1] < losses[0], losses
    after = _snapshot(lora.model, lambda n: "lora_" not in n)
    for n in before:
        np.testing.assert_array_equal(before[n], after[n], err_msg=n)


def test_lora_checkpoint_resume_with_empty_slots(tmp_path):
    """save_state/load_state round-trip a LoRA engine whose optimizer
    state holds EMPTY dicts for frozen params — the resumed step is
    bit-identical."""
    from paddle_tpu.distributed import fleet
    from paddle_tpu.framework.checkpoint import save_state, load_state
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 8, "mp_degree": 1,
                               "pp_degree": 1}
    fleet.init(is_collective=True, strategy=strategy)
    lora = LoRAModel(_gpt(41), LoRAConfig(r=2))
    opt = pt.optimizer.AdamW(learning_rate=1e-2,
                             parameters=lora.trainable_parameters())
    step = fleet.build_train_step(lora, gpt_loss_fn, opt)
    ids = pt.randint(0, 64, [8, 16])
    for _ in range(3):
        step(ids, ids)
    path = str(tmp_path / "ck")
    save_state(path, model=lora, optimizer=step)
    want = float(step(ids, ids))
    lora2 = LoRAModel(_gpt(41), LoRAConfig(r=2))
    opt2 = pt.optimizer.AdamW(learning_rate=1e-2,
                              parameters=lora2.trainable_parameters())
    step2 = fleet.build_train_step(lora2, gpt_loss_fn, opt2)
    load_state(path, model=lora2, optimizer=step2)
    got = float(step2(ids, ids))
    assert abs(want - got) < 1e-5, (want, got)
