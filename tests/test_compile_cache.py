"""Persistent compile cache: storage contract, concurrency, corruption,
degradation, and the shape-bucketing decode policy.

Covers: entry roundtrip + checksum validation, every corruption mode
(bit-flip, truncation, garbage) quarantining instead of crashing,
size-budgeted GC that never collects the just-published entry,
two PROCESSES racing on one cache dir converging without deadlock or
torn reads, unwritable-dir degradation to in-memory with exactly one
warning, digest sensitivity (shape/dtype/static args), the
FunctionCache miss->mem->hit flow, RecompileWarning dedup per
(fn, cause), and bucketed generation emitting tokens identical to the
unbucketed loop.
"""
import hashlib
import os
import subprocess
import sys
import textwrap
import warnings

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import observability as obs
from paddle_tpu.framework.compat import normalize_cost_analysis
from paddle_tpu.jit import compile_cache as cc
from paddle_tpu.jit.compile_cache import (CacheUnavailableWarning,
                                          CompileCache, FunctionCache)
from paddle_tpu.observability.metrics import MetricsRegistry
from paddle_tpu.resilience import chaos

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_cache_state():
    """Each test configures its own cache; none leaks to the next."""
    reg = MetricsRegistry()
    obs.enable(reg)
    yield reg
    obs.disable()
    cc.reset()
    cc._drop_memo_unsafe()


def _digest(s):
    return hashlib.sha256(s.encode()).hexdigest()


# ===================================================================
# store level
# ===================================================================
def test_roundtrip_and_header(tmp_path):
    c = CompileCache(str(tmp_path))
    c.put(_digest("k"), b"\x01" * 1000, meta={"label": "t"})
    assert c.get(_digest("k")) == b"\x01" * 1000
    assert c.get(_digest("other")) is None
    # crash-safe publish leaves no temp litter
    assert not [n for n in os.listdir(tmp_path) if n.endswith(".tmp")]


@pytest.mark.parametrize("mode", ["flip", "truncate", "garbage"])
def test_corrupt_entry_quarantined_not_crashed(tmp_path, mode):
    c = CompileCache(str(tmp_path))
    d = _digest("victim")
    c.put(d, b"payload-bytes" * 100)
    chaos.corrupt_cache_entry(str(tmp_path), mode=mode)
    with pytest.warns(CacheUnavailableWarning, match="quarantined"):
        assert c.get(d) is None          # miss, not an exception
    q = os.path.join(tmp_path, "quarantine")
    assert os.path.isdir(q) and len(os.listdir(q)) == 1
    # the damaged entry left the lookup namespace entirely
    assert c.get(d) is None
    assert cc.stats()["quarantined"] == 1


def test_gc_evicts_oldest_but_protects_fresh(tmp_path):
    c = CompileCache(str(tmp_path), max_bytes=3000)
    for i in range(5):
        c.put(_digest(f"e{i}"), bytes([i]) * 900)
        os.utime(c._path(_digest(f"e{i}")), (i, i))  # deterministic age
    # budget 3000 holds ~3 entries; the newest (protected) must survive
    assert c.get(_digest("e4")) is not None
    assert c.get(_digest("e0")) is None   # oldest evicted
    assert c.total_bytes() <= 3000 + 1024  # header overhead slack
    assert cc.stats()["evictions"] >= 1


def test_unwritable_dir_degrades_with_one_warning(tmp_path):
    blocker = tmp_path / "not_a_dir"
    blocker.write_text("occupied")
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        c = cc.configure(str(blocker))      # path is a file -> unwritable
        c.put(_digest("m"), b"mem-only")
        assert c.get(_digest("m")) == b"mem-only"   # in-memory fallback
        c.put(_digest("m2"), b"more")
    degraded = [x for x in w if issubclass(x.category,
                                           CacheUnavailableWarning)]
    assert len(degraded) == 1, [str(x.message) for x in w]
    assert "in-memory-only" in str(degraded[0].message)
    assert cc.stats()["degraded"] == 1


def test_two_processes_race_without_deadlock_or_torn_reads(tmp_path):
    """Two workers hammer the same digests with different payload sizes;
    lock-free last-writer-wins must never deadlock, never publish a torn
    entry (a reader validating a mixed write would quarantine it), and
    leave only whole entries behind."""
    worker = textwrap.dedent(f"""
        import sys, hashlib
        sys.path.insert(0, {REPO!r})
        from paddle_tpu.jit.compile_cache import CompileCache
        c = CompileCache(sys.argv[1], max_bytes=1 << 30)
        payload = sys.argv[2].encode() * int(sys.argv[3])
        for i in range(250):
            d = hashlib.sha256(str(i % 7).encode()).hexdigest()
            c.put(d, payload, meta={{"writer": sys.argv[2]}})
            got = c.get(d)
            assert got is not None, "published entry vanished"
        print("OK", flush=True)
    """)
    procs = [subprocess.Popen(
        [sys.executable, "-c", worker, str(tmp_path), tag, size],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
        for tag, size in (("a", "400"), ("b", "90000"))]
    for p in procs:
        out, err = p.communicate(timeout=240)   # timeout == deadlock
        assert p.returncode == 0, err
        assert "OK" in out
    # every surviving entry validates end-to-end in a fresh reader
    reader = CompileCache(str(tmp_path))
    live = [n for n in os.listdir(tmp_path) if n.endswith(".ccx")]
    assert len(live) == 7
    for n in live:
        assert reader.get(n[:-len(".ccx")]) is not None
    assert not os.path.isdir(tmp_path / "quarantine"), \
        "a torn/mixed write was published"
    assert not [n for n in os.listdir(tmp_path) if n.endswith(".tmp")]


# ===================================================================
# digests
# ===================================================================
def test_digest_sensitivity():
    import jax.numpy as jnp
    fc = FunctionCache("t", fingerprint=("src",))
    a = (jnp.ones((2, 3)),)
    assert fc.digest(a) == fc.digest((jnp.zeros((2, 3)),))  # values don't key
    assert fc.digest(a) != fc.digest((jnp.ones((2, 4)),))   # shape does
    assert fc.digest(a) != fc.digest((jnp.ones((2, 3), jnp.int32),))
    assert fc.digest(a) != fc.digest(a, static=("train",))
    fc2 = FunctionCache("t", fingerprint=("other-src",))
    assert fc.digest(a) != fc2.digest(a)                    # code identity


# ===================================================================
# FunctionCache end-to-end (non-donating program: safe to deserialize
# in-process — see the _MEMO comment for why donated ones are not)
# ===================================================================
def test_lookup_miss_mem_hit_flow(tmp_path):
    import jax
    cc.configure(str(tmp_path))
    jitted = jax.jit(lambda x: x * 2.0 + 1.0)
    args = (np.ones((4,), np.float32),)
    fc = FunctionCache("flow", fingerprint=("flow-src",))
    runner, outcome, _ = fc.lookup(jitted, args)
    assert outcome == "miss"
    np.testing.assert_allclose(np.asarray(runner(*args)), np.full(4, 3.0))
    _, outcome2, _ = fc.lookup(jitted, args)
    assert outcome2 == "mem"            # process-global memo
    # a different FunctionCache for the same program also memo-hits:
    # one live executable instance per program per process
    _, outcome3, _ = FunctionCache("flow", fingerprint=("flow-src",)
                                   ).lookup(jitted, args)
    assert outcome3 == "mem"
    # simulate a restarted process (memo gone, disk warm)
    cc._drop_memo_unsafe()
    runner4, outcome4, extra = FunctionCache(
        "flow", fingerprint=("flow-src",)).lookup(jitted, args)
    if outcome4 != "bypass":            # jax build can serialize
        assert outcome4 == "hit"
        np.testing.assert_allclose(np.asarray(runner4(*args)),
                                   np.full(4, 3.0))
    s = cc.stats()
    assert s["misses"] == 1 and s["puts"] == 1


def test_extra_metadata_roundtrips_through_store(tmp_path):
    import jax
    cc.configure(str(tmp_path))
    jitted = jax.jit(lambda x: x + 1)
    args = (np.zeros((2,), np.float32),)
    fc = FunctionCache("extra", fingerprint=())
    _, outcome, _ = fc.lookup(jitted, args,
                              extra_fn=lambda: {"treedef": "leaf", "n": 1})
    assert outcome == "miss"
    cc._drop_memo_unsafe()
    _, outcome2, extra = FunctionCache("extra", fingerprint=()).lookup(
        jitted, args)
    if outcome2 == "hit":
        assert extra == {"treedef": "leaf", "n": 1}


# ===================================================================
# compile tracker: RecompileWarning dedup per (fn, cause)
# ===================================================================
def test_recompile_warning_once_per_cause(_clean_cache_state):
    from paddle_tpu.observability import compile_tracker as ct
    obs.enable(_clean_cache_state, warn_after=1)
    owner = object()
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        for n in (4, 5, 6, 7):          # a decode loop: new length each call
            tok = ct.on_call("decode_step",
                             ct.signature_of([np.ones((1, n))]),
                             owner=owner)
            ct.finish(tok)
    recs = [x for x in w if "recompilation dominates" in str(x.message)]
    assert len(recs) == 1, [str(x.message) for x in recs]


# ===================================================================
# shape bucketing
# ===================================================================
def test_bucket_policy_ladder_and_spec():
    from paddle_tpu.text.generation import BucketPolicy
    p = BucketPolicy()
    assert p.bucket(1) == 32 and p.bucket(32) == 32
    assert p.bucket(33) == 64 and p.bucket(200) == 256
    e = BucketPolicy(buckets=[64, 128, 512])
    assert e.bucket(10) == 64 and e.bucket(128) == 128
    assert e.bucket(513) == 1024        # doubles past the last bucket
    assert BucketPolicy.from_spec("off") is None
    assert BucketPolicy.from_spec(None) is None
    assert BucketPolicy.from_spec("on").min_bucket == 32
    assert BucketPolicy.from_spec("64,128").buckets == [64, 128]


def test_bucketed_generate_matches_unbucketed(_clean_cache_state):
    from paddle_tpu.text import GPTConfig, GPTForCausalLM
    from paddle_tpu.text import generation
    pt.seed(0)
    cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=2,
                    num_heads=4, max_position_embeddings=64,
                    hidden_dropout=0.0, attention_dropout=0.0,
                    tensor_parallel=False)
    m = GPTForCausalLM(cfg)
    ids = pt.randint(0, 64, [2, 5])
    ref = generation.generate(m, ids, max_new_tokens=6)
    got = generation.generate(m, ids, max_new_tokens=6,
                              shape_buckets="on")
    np.testing.assert_array_equal(got.numpy(), ref.numpy())
    snap = {r["name"]: r for r in _clean_cache_state.snapshot()}
    assert snap["generation_bucketed_calls_total"]["value"] >= 1


def test_bucketed_generate_respects_eos(_clean_cache_state):
    from paddle_tpu.text import GPTConfig, GPTForCausalLM
    from paddle_tpu.text import generation
    pt.seed(0)
    cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=2,
                    num_heads=4, max_position_embeddings=64,
                    hidden_dropout=0.0, attention_dropout=0.0,
                    tensor_parallel=False)
    m = GPTForCausalLM(cfg)
    ids = pt.randint(0, 64, [1, 4])
    ref = generation.generate(m, ids, max_new_tokens=8, eos_token_id=3)
    got = generation.generate(m, ids, max_new_tokens=8, eos_token_id=3,
                              shape_buckets="on")
    np.testing.assert_array_equal(got.numpy(), ref.numpy())


# ===================================================================
# AOT deployment artifacts (non-donating inference program: safe to
# round-trip in-process — see the _MEMO comment for why donated
# executables are not)
# ===================================================================
_AOT_OK = cc._serializer() is not None
aot_only = pytest.mark.skipif(
    not _AOT_OK, reason="this jax build cannot serialize executables")


class _TinyNet(pt.nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc = pt.nn.Linear(8, 4)

    def forward(self, x):
        return pt.nn.functional.relu(self.fc(x))


def _export_aot(tmp_path):
    from paddle_tpu.jit.save_load import InputSpec, save_inference
    pt.seed(0)
    m = _TinyNet()
    m.eval()
    x = pt.to_tensor(np.random.RandomState(0).randn(2, 8)
                     .astype("float32"))
    path = os.path.join(str(tmp_path), "deploy")
    save_inference(m, path, [InputSpec([2, 8], "float32", "x")], aot=True)
    return path, x, m(x).numpy()


@aot_only
def test_aot_roundtrip_serves_without_compilation(tmp_path):
    from paddle_tpu.jit.save_load import load_inference
    path, x, ref = _export_aot(tmp_path)
    assert os.path.exists(os.path.join(path, "model.aotexec"))
    tl = load_inference(path)
    assert tl.is_aot
    np.testing.assert_allclose(tl(x).numpy(), ref, atol=1e-6)


@aot_only
def test_aot_refused_with_reason_on_stamp_mismatch(tmp_path,
                                                   _clean_cache_state):
    import json as _json
    from paddle_tpu.jit.save_load import (AOTIncompatible, load_inference)
    path, x, ref = _export_aot(tmp_path)
    meta_path = os.path.join(path, "inference_meta.json")
    with open(meta_path) as f:
        meta = _json.load(f)
    meta["aot"]["jax"] = "0.0.0-elsewhere"
    with open(meta_path, "w") as f:
        _json.dump(meta, f)
    # refuse-with-reason: the warning names exactly what diverged,
    # the portable StableHLO program still serves
    with pytest.warns(UserWarning, match="jax version mismatch"):
        tl = load_inference(path)
    assert not tl.is_aot
    np.testing.assert_allclose(tl(x).numpy(), ref, atol=1e-6)
    snap = {r["name"]: r for r in _clean_cache_state.snapshot()}
    assert snap["aot_artifact_refused_total"]["value"] >= 1
    # strict deployments turn the silent-recompile fallback into an error
    with pytest.raises(AOTIncompatible, match="jax version mismatch"):
        load_inference(path, strict_aot=True)


@aot_only
def test_aot_damaged_artifact_falls_back(tmp_path):
    from paddle_tpu.jit.save_load import load_inference
    path, x, ref = _export_aot(tmp_path)
    with open(os.path.join(path, "model.aotexec"), "r+b") as f:
        f.seek(8)
        f.write(b"\xa5" * 16)
    with pytest.warns(UserWarning, match="checksum mismatch"):
        tl = load_inference(path)
    assert not tl.is_aot
    np.testing.assert_allclose(tl(x).numpy(), ref, atol=1e-6)


def test_config_fingerprint_keys_hyperparams_not_runtime_state():
    """Instance constants the trace bakes in (momentum) must split the
    key; mutable runtime counters a checkpoint restore advances
    (optimizer step count) must NOT — else every warm restart misses."""
    from paddle_tpu import nn, optimizer as opt
    m1, m2 = nn.Linear(2, 1), nn.Linear(2, 1)
    o1 = opt.Momentum(learning_rate=0.05, momentum=0.9,
                      parameters=m1.parameters())
    o2 = opt.Momentum(learning_rate=0.05, momentum=0.5,
                      parameters=m2.parameters())
    assert cc.config_fingerprint(o1) != cc.config_fingerprint(o2)
    before = cc.config_fingerprint(o1)
    o1._step_count = 7              # what a restore mutates
    assert cc.config_fingerprint(o1) == before
    # and a FunctionCache keyed on it splits the digest
    import jax.numpy as jnp
    fc = FunctionCache("t", fingerprint=("src",))
    a = (jnp.ones((2, 3)),)
    assert (fc.digest(a, static=(cc.config_fingerprint(o1),))
            != fc.digest(a, static=(cc.config_fingerprint(o2),)))


# ===================================================================
# satellites riding along
# ===================================================================
def test_normalize_cost_analysis_shapes():
    assert normalize_cost_analysis(None) == {}
    assert normalize_cost_analysis([]) == {}
    assert normalize_cost_analysis({"flops": 2.0}) == {"flops": 2.0}
    assert normalize_cost_analysis([{"flops": 4.0}]) == {"flops": 4.0}
    assert normalize_cost_analysis(42) == {}
