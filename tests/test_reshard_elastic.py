"""Elastic recovery engine: cross-mesh resharding + collective robustness.

Covers: the arXiv:2112.01075 plan decomposition (shrink -> allgather,
grow -> dynamic-slice, axis permutation -> all-to-all), bit-exactness of
save-under-mesh-A -> reshard -> restore-under-mesh-B against the
host-gather reference, the checkpoint-level Resharder path, the
collective timeout/retry policy driven through the collective.timeout /
collective.hang chaos sites, the launch heartbeat, and back-compat with
pre-resilience checkpoints that carry no mesh snapshot.
"""
import os
import tempfile
import time
import warnings

import numpy as np
import pytest

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.distributed import collective as coll
from paddle_tpu.distributed import mesh as mesh_mod
from paddle_tpu.framework.checkpoint import load_state, probe, save_state
from paddle_tpu.observability import metrics
from paddle_tpu.resilience import chaos
from paddle_tpu.resilience.manager import CheckpointManager
from paddle_tpu.resilience.reshard import (
    Layout, Resharder, layout_of, place_from_host, plan_reshard,
    reshard_array)


@pytest.fixture(autouse=True)
def _clean_runtime():
    prev = dict(mesh_mod._state)
    yield
    mesh_mod._state.update(prev)
    chaos.uninstall()
    coll.configure_collectives()


def _mesh(n, axes=("dp",), shape=None):
    devs = np.asarray(jax.devices()[:n])
    if shape is not None:
        devs = devs.reshape(shape)
    return Mesh(devs, axes)


def _sharded(shape, mesh, spec, seed=0):
    host = np.random.RandomState(seed).randn(*shape).astype(np.float32)
    return host, jax.device_put(host, NamedSharding(mesh, spec))


def _assert_matches_host(out, host, dst_sharding):
    """Bit-exact vs the host-gather reference, shard by shard and as a
    whole."""
    assert out.sharding == dst_sharding
    np.testing.assert_array_equal(np.asarray(out), host)
    for s in out.addressable_shards:
        np.testing.assert_array_equal(np.asarray(s.data), host[s.index])


# ===================================================================
# plan decomposition (arXiv:2112.01075)
# ===================================================================
def test_plan_shrink_classifies_allgather():
    src = Layout([("dp",)], {"dp": 4})
    dst = NamedSharding(_mesh(2), P("dp"))
    plan = plan_reshard((8, 4), np.float32, src, dst)
    kinds = [k for k, _, _ in plan.ops]
    assert "allgather" in kinds
    assert plan.mesh_changed
    assert plan.bytes_moved > 0
    # shrink 4 -> 2: peak per-device buffer is the COARSER (target) shard
    assert plan.peak_buffer_bytes == 8 * 4 * 4 // 2


def test_plan_grow_classifies_slice():
    src = Layout([("dp",)], {"dp": 2})
    dst = NamedSharding(_mesh(4), P("dp"))
    plan = plan_reshard((8, 4), np.float32, src, dst)
    kinds = [k for k, _, _ in plan.ops]
    assert "slice" in kinds and "allgather" not in kinds
    # grow 2 -> 4: nothing coarser than the source shard is materialized
    assert plan.peak_buffer_bytes == 8 * 4 * 4 // 2


def test_plan_axis_permutation_classifies_all_to_all():
    mesh = _mesh(4, axes=("x", "y"), shape=(2, 2))
    src = Layout([("x",), ("y",)], {"x": 2, "y": 2})
    dst = NamedSharding(mesh, P("y", "x"))
    plan = plan_reshard((4, 4), np.float32, src, dst)
    assert [k for k, _, _ in plan.ops] == ["all_to_all"]
    assert not plan.mesh_changed


def test_plan_unknown_source_is_mesh_change():
    dst = NamedSharding(_mesh(2), P("dp"))
    plan = plan_reshard((8, 4), np.float32, None, dst)
    assert plan.mesh_changed
    assert plan.bytes_moved >= 8 * 4 * 4   # full payload relocates


# ===================================================================
# save-under-A -> reshard -> restore-under-B, bit-exact vs host-gather
# ===================================================================
@pytest.mark.parametrize("n_src,n_dst", [(4, 2),   # shrink
                                         (2, 4)])  # grow
def test_place_from_host_world_resize_bit_exact(n_src, n_dst):
    mesh_a = _mesh(n_src)
    host, arr = _sharded((8, 4), mesh_a, P("dp"), seed=n_src)
    src = layout_of(arr)
    assert src is not None and src.axes == {"dp": n_src}
    dst = NamedSharding(_mesh(n_dst), P("dp"))
    out = place_from_host(np.asarray(arr), dst, src=src)
    _assert_matches_host(out, host, dst)


def test_place_from_host_axis_permutation_bit_exact():
    mesh = _mesh(4, axes=("x", "y"), shape=(2, 2))
    host, arr = _sharded((4, 6), mesh, P("x", "y"), seed=3)
    dst = NamedSharding(mesh, P("y", "x"))
    out = place_from_host(np.asarray(arr), dst, src=layout_of(arr))
    _assert_matches_host(out, host, dst)


@pytest.mark.parametrize("n_src,n_dst", [(4, 2), (2, 4)])
def test_reshard_array_live_world_resize_bit_exact(n_src, n_dst):
    mesh_a = _mesh(n_src)
    host, arr = _sharded((8, 4), mesh_a, P("dp"), seed=10 + n_src)
    dst = NamedSharding(_mesh(n_dst), P("dp"))
    out = reshard_array(arr, dst)
    _assert_matches_host(out, host, dst)


def test_reshard_array_same_sharding_is_identity():
    mesh = _mesh(2)
    _, arr = _sharded((4, 4), mesh, P("dp"))
    assert reshard_array(arr, arr.sharding) is arr


# ===================================================================
# checkpoint-level Resharder (framework.checkpoint.load_state route)
# ===================================================================
def test_checkpoint_resharder_routes_device_path(tmp_path):
    mesh_a = _mesh(4)
    paddle.seed(5)
    model = nn.Linear(4, 2)
    w_host = np.asarray(model.weight.numpy()).copy()
    b_host = np.asarray(model.bias.numpy()).copy()
    model.weight._inplace_assign(
        jax.device_put(model.weight._array, NamedSharding(mesh_a, P("dp"))))
    path = str(tmp_path / "ckpt")
    save_state(path, model=model, step=1)
    meta = probe(path)
    # save-time layouts recorded for the sharded leaf
    assert "model/weight" in meta.get("layouts", {})
    assert Layout.from_json(meta["layouts"]["model/weight"]).axes == \
        {"dp": 4}

    mesh_b = _mesh(2)
    paddle.seed(99)                       # values must come from the ckpt
    model2 = nn.Linear(4, 2)
    rs = Resharder({"model/weight": NamedSharding(mesh_b, P("dp")),
                    "model/bias": NamedSharding(mesh_b, P())},
                   layouts=meta.get("layouts"))
    load_state(path, model=model2, resharder=rs)
    assert rs.arrays == 2 and rs.skipped == 0
    np.testing.assert_array_equal(np.asarray(model2.weight.numpy()), w_host)
    np.testing.assert_array_equal(np.asarray(model2.bias.numpy()), b_host)


def test_resharder_unknown_path_falls_through():
    rs = Resharder({"model/weight": NamedSharding(_mesh(2), P("dp"))})
    assert rs.maybe_place("model/other", np.ones((4,), np.float32)) is None
    assert rs.skipped == 1


def test_resharder_parent_prefix_covers_slots():
    mesh = _mesh(2)
    rs = Resharder({"optimizer/w": lambda shape: NamedSharding(mesh, P())})
    out = rs.maybe_place("optimizer/w/velocity",
                         np.ones((4, 2), np.float32))
    assert out is not None and rs.arrays == 1


# ===================================================================
# back-compat: pre-resilience checkpoints without a mesh snapshot
# ===================================================================
def test_restore_tolerates_checkpoint_without_mesh_snapshot(tmp_path):
    paddle.seed(6)
    model = nn.Linear(4, 2)
    w = np.asarray(model.weight.numpy()).copy()
    root = str(tmp_path)
    # write the checkpoint with save_state directly: no manager, so no
    # "mesh" key in extra — the pre-PR-5 on-disk format
    mgr = CheckpointManager(root)
    save_state(mgr.path_for(3), model=model, step=3)
    assert "mesh" not in (probe(mgr.path_for(3)).get("extra") or {})

    paddle.seed(77)
    model2 = nn.Linear(4, 2)
    with warnings.catch_warnings(record=True) as ws:
        warnings.simplefilter("always")
        meta = mgr.restore(model=model2)
    assert meta["step"] == 3
    np.testing.assert_array_equal(np.asarray(model2.weight.numpy()), w)
    msgs = [str(x.message) for x in ws
            if "no mesh snapshot" in str(x.message)]
    assert len(msgs) == 1
    # one-time: a second restore through the same manager stays quiet
    with warnings.catch_warnings(record=True) as ws2:
        warnings.simplefilter("always")
        mgr.restore(model=model2)
    assert not [x for x in ws2 if "no mesh snapshot" in str(x.message)]


# ===================================================================
# collective timeout/retry policy through the chaos sites
# ===================================================================
def _retry_counts(op="all_reduce"):
    reg = metrics.registry()
    return (reg.counter("collective_timeout_total", op=op).value,
            reg.counter("collective_retry_total", op=op).value)


def test_collective_timeout_retried_by_policy():
    coll.configure_collectives(timeout=30.0, retries=2, backoff_base=0.01)
    t0, r0 = _retry_counts()
    x = paddle.to_tensor(np.ones((4,), np.float32))
    with chaos.scoped("collective.timeout@1"):
        with warnings.catch_warnings(record=True) as ws:
            warnings.simplefilter("always")
            out = coll.all_reduce(x)
    np.testing.assert_array_equal(np.asarray(out.numpy()), np.ones((4,)))
    t1, r1 = _retry_counts()
    assert t1 - t0 == 1 and r1 - r0 == 1
    # the straggler warning names the mesh axis
    assert any("straggler" in str(x.message) and "axis" in str(x.message)
               for x in ws)


def test_collective_timeout_exhausted_raises():
    coll.configure_collectives(timeout=30.0, retries=1, backoff_base=0.01)
    x = paddle.to_tensor(np.ones((4,), np.float32))
    with chaos.scoped("collective.timeout@1*5"):
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            with pytest.raises(coll.CollectiveTimeout):
                coll.all_reduce(x)


def test_collective_hang_abandoned_by_watchdog():
    """A real stall (not an injected exception): the attempt thread
    sleeps past the deadline, the watchdog abandons it, the retry
    succeeds."""
    coll.configure_collectives(timeout=0.2, retries=1, backoff_base=0.01)
    t0, r0 = _retry_counts()
    x = paddle.to_tensor(np.ones((4,), np.float32))
    start = time.monotonic()
    with chaos.scoped("collective.hang@1"):
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            out = coll.all_reduce(x)
    assert time.monotonic() - start < 5.0   # abandoned, not slept out
    np.testing.assert_array_equal(np.asarray(out.numpy()), np.ones((4,)))
    t1, r1 = _retry_counts()
    assert t1 - t0 == 1 and r1 - r0 == 1


def test_collective_policy_all_defaults_clears():
    coll.configure_collectives(timeout=5.0, retries=1)
    assert coll.collective_policy() is not None
    coll.configure_collectives()            # all-defaults clears
    assert coll.collective_policy() is None


def test_collective_fail_once_counted_and_retried():
    coll.configure_collectives(retries=1, backoff_base=0.01)
    reg = metrics.registry()
    f0 = reg.counter("collective_failures_total", op="all_reduce").value
    x = paddle.to_tensor(np.ones((2,), np.float32))
    with chaos.scoped("collective.fail_once@1"):
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            coll.all_reduce(x)
    assert reg.counter("collective_failures_total",
                       op="all_reduce").value - f0 == 1


# ===================================================================
# launch heartbeat
# ===================================================================
def test_heartbeat_beats_and_stops(tmp_path):
    from paddle_tpu.distributed.launch import heartbeat as hb
    path = str(tmp_path / "hb.0")
    try:
        h = hb.start_heartbeat(path=path, interval=0.05)
        assert h is not None and os.path.exists(path)
        # backdate the file: the beating thread must refresh its mtime
        os.utime(path, (time.time() - 60.0, time.time() - 60.0))
        deadline = time.monotonic() + 5.0
        while time.time() - os.path.getmtime(path) > 1.0 \
                and time.monotonic() < deadline:
            time.sleep(0.05)
        assert time.time() - os.path.getmtime(path) <= 1.0
        # second call returns the running singleton
        assert hb.start_heartbeat(path=str(tmp_path / "other")) is h
    finally:
        hb.stop_heartbeat()
    assert hb._ACTIVE is None


def test_heartbeat_noop_without_env(monkeypatch):
    from paddle_tpu.distributed.launch import heartbeat as hb
    monkeypatch.delenv("PT_HEARTBEAT_FILE", raising=False)
    assert hb.start_heartbeat() is None


def test_worker_heartbeat_stale_detection(tmp_path):
    from paddle_tpu.distributed.launch import _Worker

    class _Args:
        script, script_args, log_dir = "x.py", [], None
        nnodes = node_rank = 1
        nproc_per_node = 2

    class _FakeProc:
        def poll(self):
            return None

    w = _Worker(_Args(), 0, hb_dir=str(tmp_path))
    w.proc = _FakeProc()
    w.started_at = time.monotonic() - 60.0
    now = time.monotonic()
    # no heartbeat file ever written: not participating, never stale
    assert not w.heartbeat_stale(1.0, now)
    with open(w.hb_path, "w"):
        pass
    os.utime(w.hb_path, (time.time() - 30.0, time.time() - 30.0))
    # mtime is only a change detector: the first observation arms the
    # monotonic staleness clock (a wall-clock step / NTP jump must not
    # declare the whole fleet hung at once)
    assert not w.heartbeat_stale(1.0, now)
    assert w.heartbeat_stale(1.0, now + 2.0)    # silent past timeout
    os.utime(w.hb_path, None)
    assert not w.heartbeat_stale(1.0, now + 2.0)   # fresh beat -> alive
    assert w.heartbeat_stale(1.0, now + 4.0)    # silent again -> hang
