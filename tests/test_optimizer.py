"""Optimizer + LR scheduler tests (SURVEY §4)."""
import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.nn as nn
from paddle_tpu.optimizer import lr as lr_mod


def _quadratic_converges(opt_cls, lr=0.1, steps=120, tol=1e-2, **kw):
    target = pt.to_tensor([3.0, -2.0])
    x = pt.parameter([0.0, 0.0])
    opt = opt_cls(learning_rate=lr, parameters=[x], **kw)
    for _ in range(steps):
        loss = ((x - target) ** 2).sum()
        loss.backward()
        opt.step()
        opt.clear_grad()
    np.testing.assert_allclose(x.numpy(), target.numpy(), atol=tol)


def test_sgd():
    _quadratic_converges(pt.optimizer.SGD, lr=0.1)


def test_momentum():
    _quadratic_converges(pt.optimizer.Momentum, lr=0.05)


def test_adam():
    _quadratic_converges(pt.optimizer.Adam, lr=0.2)


def test_adamw():
    _quadratic_converges(pt.optimizer.AdamW, lr=0.2, weight_decay=0.0)


def test_rmsprop():
    _quadratic_converges(pt.optimizer.RMSProp, lr=0.05)


def test_adagrad():
    _quadratic_converges(pt.optimizer.Adagrad, lr=0.5, tol=0.15)


def test_lamb():
    _quadratic_converges(pt.optimizer.Lamb, lr=0.05, tol=0.3)


def test_adafactor():
    _quadratic_converges(pt.optimizer.Adafactor, lr=0.5, tol=0.3)


def test_adam_matches_reference_formula():
    # one step of Adam from zero state: update = lr * g_hat / (sqrt(v_hat)+eps)
    x = pt.parameter([1.0])
    opt = pt.optimizer.Adam(learning_rate=0.1, parameters=[x])
    (x * 2.0).sum().backward()  # grad = 2
    opt.step()
    g = 2.0
    m = 0.1 * g
    v = 0.001 * g * g
    mhat = m / 0.1
    vhat = v / 0.001
    expect = 1.0 - 0.1 * mhat / (np.sqrt(vhat) + 1e-8)
    np.testing.assert_allclose(x.numpy(), [expect], rtol=1e-5)


def test_adamw_decoupled_decay():
    x = pt.parameter([1.0])
    opt = pt.optimizer.AdamW(learning_rate=0.1, parameters=[x],
                             weight_decay=0.5)
    (x * 0.0).sum().backward()  # zero grad → pure decay
    opt.step()
    np.testing.assert_allclose(x.numpy(), [1.0 - 0.1 * 0.5 * 1.0], rtol=1e-5)


def test_grad_clip_in_optimizer():
    x = pt.parameter([10.0])
    opt = pt.optimizer.SGD(learning_rate=1.0, parameters=[x],
                           grad_clip=nn.ClipGradByGlobalNorm(1.0))
    (x * 10.0).sum().backward()  # grad = 10 → clipped to 1
    opt.step()
    np.testing.assert_allclose(x.numpy(), [9.0], rtol=1e-5)


def test_optimizer_state_dict():
    x = pt.parameter([1.0])
    opt = pt.optimizer.Adam(learning_rate=0.1, parameters=[x])
    (x * 2).sum().backward()
    opt.step()
    sd = opt.state_dict()
    assert sd["step"] == 1
    opt2 = pt.optimizer.Adam(learning_rate=0.1, parameters=[x])
    opt2.set_state_dict(sd)
    assert opt2._step_count == 1


def test_lr_schedulers():
    s = lr_mod.StepDecay(0.1, step_size=2, gamma=0.5)
    lrs = []
    for _ in range(5):
        lrs.append(s())
        s.step()
    np.testing.assert_allclose(lrs, [0.1, 0.1, 0.05, 0.05, 0.025])

    c = lr_mod.CosineAnnealingDecay(1.0, T_max=10)
    assert c() == pytest.approx(1.0)
    for _ in range(10):
        c.step()
    assert c() == pytest.approx(0.0, abs=1e-6)

    w = lr_mod.LinearWarmup(0.1, warmup_steps=10, start_lr=0.0, end_lr=0.1)
    assert w() == pytest.approx(0.0)
    for _ in range(10):
        w.step()
    assert w() == pytest.approx(0.1)

    n = lr_mod.NoamDecay(d_model=512, warmup_steps=100)
    vals = []
    for _ in range(200):
        n.step()
        vals.append(n())
    assert np.argmax(vals) == pytest.approx(99, abs=2)


def test_scheduler_in_optimizer():
    x = pt.parameter([1.0])
    sched = lr_mod.StepDecay(0.1, step_size=1, gamma=0.1)
    opt = pt.optimizer.SGD(learning_rate=sched, parameters=[x])
    (x * 1.0).sum().backward()
    opt.step()
    np.testing.assert_allclose(x.numpy(), [0.9], rtol=1e-5)
    sched.step()
    opt.clear_grad()
    (x * 1.0).sum().backward()
    opt.step()
    np.testing.assert_allclose(x.numpy(), [0.89], rtol=1e-4)


def test_multi_precision_master_weights():
    import jax.numpy as jnp
    x = pt.parameter(np.ones(4, np.float32))
    x._inplace_assign(x._array.astype(jnp.bfloat16))
    opt = pt.optimizer.Adam(learning_rate=0.01, parameters=[x],
                            multi_precision=True)
    (x.astype("float32") * 2).sum().backward()
    opt.step()
    assert x.dtype == jnp.bfloat16
    assert "master" in opt._state[0]
    assert opt._state[0]["master"].dtype == jnp.float32
