"""Speculative decoding (text/decode.py speculative_generate).

The load-bearing property: exact-match acceptance makes the output
IDENTICAL to the target model's greedy decode, for ANY draft — a bad
draft only lowers the acceptance rate, never changes tokens.
"""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.text import GPTConfig, GPTForCausalLM
from paddle_tpu.text.decode import jit_generate, speculative_generate


def _model(layers, hidden, seed):
    pt.seed(seed)
    cfg = GPTConfig(vocab_size=96, hidden_size=hidden, num_layers=layers,
                    num_heads=4, max_position_embeddings=96,
                    hidden_dropout=0.0, attention_dropout=0.0)
    m = GPTForCausalLM(cfg)
    m.eval()
    return m


@pytest.fixture(scope="module")
def target():
    return _model(3, 48, 11)


@pytest.fixture(scope="module")
def prompt():
    return pt.to_tensor(
        np.array([[5, 17, 40, 3, 88, 2, 64, 9]], np.int64))


class TestSpeculative:
    def test_matches_greedy_with_weak_draft(self, target, prompt):
        draft = _model(1, 16, 99)   # unrelated weights: low acceptance
        want = jit_generate(target, prompt, max_new_tokens=16).numpy()
        got = speculative_generate(target, draft, prompt,
                                   max_new_tokens=16,
                                   num_speculative_tokens=4).numpy()
        np.testing.assert_array_equal(got, want)

    def test_matches_greedy_with_perfect_draft(self, target, prompt):
        # draft == target: every proposal accepted, still identical
        want = jit_generate(target, prompt, max_new_tokens=12).numpy()
        got = speculative_generate(target, target, prompt,
                                   max_new_tokens=12,
                                   num_speculative_tokens=3).numpy()
        np.testing.assert_array_equal(got, want)

    def test_various_k(self, target, prompt):
        draft = _model(1, 16, 7)
        want = jit_generate(target, prompt, max_new_tokens=10).numpy()
        for k in (1, 2, 5):
            got = speculative_generate(target, draft, prompt,
                                       max_new_tokens=10,
                                       num_speculative_tokens=k).numpy()
            np.testing.assert_array_equal(got, want)

    def test_draft_swap_recompiles(self, target, prompt):
        # the compiled program closes over the draft's structure: swapping
        # to a draft with a DIFFERENT architecture must not reuse it
        want = jit_generate(target, prompt, max_new_tokens=8).numpy()
        d1 = _model(1, 16, 21)
        d2 = _model(2, 32, 22)   # different layer count + width
        for d in (d1, d2, d1):
            got = speculative_generate(target, d, prompt,
                                       max_new_tokens=8,
                                       num_speculative_tokens=3).numpy()
            np.testing.assert_array_equal(got, want)

    def test_generate_api_routes_draft_model(self, target, prompt):
        from paddle_tpu.text.generation import generate
        draft = _model(1, 16, 31)
        want = jit_generate(target, prompt, max_new_tokens=6).numpy()
        got_t = generate(target, prompt, max_new_tokens=6,
                         draft_model=draft)
        plain = generate(target, prompt, max_new_tokens=6)
        assert str(got_t.dtype) == str(plain.dtype)   # path-consistent ids
        np.testing.assert_array_equal(got_t.numpy(), want)
        with pytest.raises(NotImplementedError, match="greedy-only"):
            generate(target, prompt, draft_model=draft, do_sample=True)

    def test_batch_gt1_raises(self, target):
        ids = pt.to_tensor(np.zeros((2, 4), np.int64))
        draft = _model(1, 16, 7)
        with pytest.raises(NotImplementedError, match="batch 1"):
            speculative_generate(target, draft, ids)
