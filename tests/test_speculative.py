"""Speculative decoding (text/decode.py speculative_generate).

The load-bearing property: exact-match acceptance makes the output
IDENTICAL to the target model's greedy decode, for ANY draft — a bad
draft only lowers the acceptance rate, never changes tokens.
"""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.text import GPTConfig, GPTForCausalLM
from paddle_tpu.text.decode import jit_generate, speculative_generate


def _model(layers, hidden, seed):
    pt.seed(seed)
    cfg = GPTConfig(vocab_size=96, hidden_size=hidden, num_layers=layers,
                    num_heads=4, max_position_embeddings=96,
                    hidden_dropout=0.0, attention_dropout=0.0)
    m = GPTForCausalLM(cfg)
    m.eval()
    return m


@pytest.fixture(scope="module")
def target():
    return _model(3, 48, 11)


@pytest.fixture(scope="module")
def prompt():
    return pt.to_tensor(
        np.array([[5, 17, 40, 3, 88, 2, 64, 9]], np.int64))


class TestSpeculative:
    def test_matches_greedy_with_weak_draft(self, target, prompt):
        draft = _model(1, 16, 99)   # unrelated weights: low acceptance
        want = jit_generate(target, prompt, max_new_tokens=16).numpy()
        got = speculative_generate(target, draft, prompt,
                                   max_new_tokens=16,
                                   num_speculative_tokens=4).numpy()
        np.testing.assert_array_equal(got, want)

    def test_matches_greedy_with_perfect_draft(self, target, prompt):
        # draft == target: every proposal accepted, still identical
        want = jit_generate(target, prompt, max_new_tokens=12).numpy()
        got = speculative_generate(target, target, prompt,
                                   max_new_tokens=12,
                                   num_speculative_tokens=3).numpy()
        np.testing.assert_array_equal(got, want)

    def test_various_k(self, target, prompt):
        draft = _model(1, 16, 7)
        want = jit_generate(target, prompt, max_new_tokens=10).numpy()
        for k in (1, 2, 5):
            got = speculative_generate(target, draft, prompt,
                                       max_new_tokens=10,
                                       num_speculative_tokens=k).numpy()
            np.testing.assert_array_equal(got, want)

    def test_draft_swap_recompiles(self, target, prompt):
        # the compiled program closes over the draft's structure: swapping
        # to a draft with a DIFFERENT architecture must not reuse it
        want = jit_generate(target, prompt, max_new_tokens=8).numpy()
        d1 = _model(1, 16, 21)
        d2 = _model(2, 32, 22)   # different layer count + width
        for d in (d1, d2, d1):
            got = speculative_generate(target, d, prompt,
                                       max_new_tokens=8,
                                       num_speculative_tokens=3).numpy()
            np.testing.assert_array_equal(got, want)

    def test_generate_api_routes_draft_model(self, target, prompt):
        from paddle_tpu.text.generation import generate
        draft = _model(1, 16, 31)
        want = jit_generate(target, prompt, max_new_tokens=6).numpy()
        got_t = generate(target, prompt, max_new_tokens=6,
                         draft_model=draft)
        plain = generate(target, prompt, max_new_tokens=6)
        assert str(got_t.dtype) == str(plain.dtype)   # path-consistent ids
        np.testing.assert_array_equal(got_t.numpy(), want)
        # sampling routes through the stochastic acceptance path
        out = generate(target, prompt, max_new_tokens=6, draft_model=draft,
                       do_sample=True, temperature=1.2).numpy()
        assert out.shape == (1, prompt.shape[1] + 6)

    def test_batched_greedy_matches_jit_generate(self, target):
        # per-row cache positions: rows accept DIFFERENT draft prefixes
        # each round yet every row must equal its own greedy decode
        draft = _model(1, 16, 7)
        ids = pt.to_tensor(np.array(
            [[5, 17, 40, 3], [1, 2, 3, 4], [90, 8, 77, 6]], np.int64))
        want = jit_generate(target, ids, max_new_tokens=12).numpy()
        got = speculative_generate(target, draft, ids, max_new_tokens=12,
                                   num_speculative_tokens=3).numpy()
        np.testing.assert_array_equal(got, want)

    def test_batched_eos_matches_jit_generate(self, target):
        draft = _model(1, 16, 7)
        ids = pt.to_tensor(np.array(
            [[5, 17, 40, 3], [1, 2, 3, 4], [90, 8, 77, 6]], np.int64))
        plain = jit_generate(target, ids, max_new_tokens=12).numpy()
        eos = int(plain[0, 4 + 3])        # a token greedy REALLY emits
        want = jit_generate(target, ids, max_new_tokens=12,
                            eos_token_id=eos).numpy()
        got = speculative_generate(target, draft, ids, max_new_tokens=12,
                                   num_speculative_tokens=4,
                                   eos_token_id=eos).numpy()
        np.testing.assert_array_equal(got, want)


class TestSpeculativeSampling:
    """Stochastic acceptance (Leviathan et al.): accept draft x with prob
    min(1, p(x)/q(x)), resample rejections from norm(max(p-q, 0)) — the
    OUTPUT DISTRIBUTION equals direct sampling from the target, for any
    draft.  Checked distribution-level (total variation on marginals)."""

    def test_matches_direct_sampling_distribution(self):
        import jax
        tgt = _small_vocab_model(2, 32, 5)
        drf = _small_vocab_model(1, 16, 77)
        B, R, NEW = 256, 4, 3
        prompt = pt.to_tensor(
            np.tile(np.array([[3, 9, 1, 14]], np.int64), (B, 1)))

        def collect(fn):
            return np.concatenate(
                [fn(jax.random.PRNGKey(1000 + r))[:, 4:]
                 for r in range(R)], 0)

        direct = collect(lambda k: jit_generate(
            tgt, prompt, max_new_tokens=NEW, do_sample=True,
            temperature=1.2, seed_key=k).numpy())
        spec = collect(lambda k: speculative_generate(
            tgt, drf, prompt, max_new_tokens=NEW, do_sample=True,
            temperature=1.2, num_speculative_tokens=3, seed_key=k).numpy())
        for pos in range(NEW):
            cd = np.bincount(direct[:, pos], minlength=16) / len(direct)
            cs = np.bincount(spec[:, pos], minlength=16) / len(spec)
            tv = 0.5 * np.abs(cd - cs).sum()
            # 1024 samples, vocab 16: sampling noise ~0.07; equal laws
            # stay well under 0.15, a wrong acceptance rule does not
            assert tv < 0.15, (pos, tv)

    def test_topk_support(self, ):
        import jax
        tgt = _model(3, 48, 11)
        drf = _model(1, 16, 99)
        ids = pt.to_tensor(np.array(
            [[5, 17, 40, 3], [1, 2, 3, 4], [90, 8, 77, 6]], np.int64))
        out = speculative_generate(
            tgt, drf, ids, max_new_tokens=8, do_sample=True, top_k=5,
            num_speculative_tokens=3,
            seed_key=jax.random.PRNGKey(0)).numpy()
        # teacher-force the output: every generated token must be inside
        # the TARGET's top-5 for its prefix (draft proposals outside the
        # filtered support must never survive acceptance/resampling)
        from paddle_tpu.autograd import engine
        with engine.no_grad():
            lg = tgt(pt.to_tensor(out.astype(np.int64))).numpy()
        for r in range(out.shape[0]):
            for i in range(4, out.shape[1]):
                topk = np.argsort(lg[r, i - 1])[-5:]
                assert out[r, i] in topk, (r, i)


def _small_vocab_model(layers, hidden, seed):
    pt.seed(seed)
    cfg = GPTConfig(vocab_size=16, hidden_size=hidden, num_layers=layers,
                    num_heads=4, max_position_embeddings=64,
                    hidden_dropout=0.0, attention_dropout=0.0)
    m = GPTForCausalLM(cfg)
    m.eval()
    return m
