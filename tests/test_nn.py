"""nn layers: shapes, train/eval, state_dict (SURVEY §4)."""
import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F


def test_linear():
    l = nn.Linear(8, 4)
    x = pt.randn([2, 8])
    assert l(x).shape == [2, 4]
    assert l.weight.shape == [8, 4]
    assert not l.weight.stop_gradient


def test_embedding():
    e = nn.Embedding(10, 6, padding_idx=0)
    ids = pt.to_tensor([[1, 2], [0, 3]])
    out = e(ids)
    assert out.shape == [2, 2, 6]
    np.testing.assert_allclose(out.numpy()[1, 0], np.zeros(6))


def test_conv2d():
    c = nn.Conv2D(3, 8, 3, stride=2, padding=1)
    x = pt.randn([2, 3, 16, 16])
    assert c(x).shape == [2, 8, 8, 8]
    g = nn.Conv2D(8, 8, 3, padding=1, groups=2)
    assert g(c(x)).shape == [2, 8, 8, 8]


def test_pooling():
    x = pt.randn([2, 4, 8, 8])
    assert nn.MaxPool2D(2)(x).shape == [2, 4, 4, 4]
    assert nn.AvgPool2D(2)(x).shape == [2, 4, 4, 4]
    assert nn.AdaptiveAvgPool2D((1, 1))(x).shape == [2, 4, 1, 1]
    np.testing.assert_allclose(
        nn.AdaptiveAvgPool2D((1, 1))(x).numpy()[:, :, 0, 0],
        x.numpy().mean(axis=(2, 3)), rtol=1e-5)


def test_layer_norm():
    ln = nn.LayerNorm(16)
    x = pt.randn([4, 16])
    out = ln(x)
    np.testing.assert_allclose(out.numpy().mean(-1), np.zeros(4), atol=1e-5)
    np.testing.assert_allclose(out.numpy().std(-1), np.ones(4), atol=1e-2)


def test_rms_norm():
    rn = nn.RMSNorm(16)
    x = pt.randn([4, 16])
    out = rn(x)
    rms = np.sqrt((out.numpy() ** 2).mean(-1))
    np.testing.assert_allclose(rms, np.ones(4), atol=1e-2)


def test_batch_norm_train_eval():
    bn = nn.BatchNorm2D(4)
    x = pt.randn([8, 4, 5, 5])
    bn.train()
    out = bn(x)
    # running stats moved off init
    assert abs(bn._mean.numpy()).max() > 0
    bn.eval()
    out_eval = bn(x)
    assert out_eval.shape == out.shape


def test_dropout_train_eval():
    d = nn.Dropout(0.5)
    x = pt.ones([1000])
    d.train()
    y = d(x)
    assert (y.numpy() == 0).mean() > 0.3
    d.eval()
    np.testing.assert_allclose(d(x).numpy(), x.numpy())


def test_sequential_and_containers():
    m = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    assert m(pt.randn([3, 4])).shape == [3, 2]
    assert len(m) == 3
    ll = nn.LayerList([nn.Linear(2, 2) for _ in range(3)])
    assert len(ll) == 3
    ll.append(nn.Linear(2, 2))
    assert len(ll) == 4
    ld = nn.LayerDict({"a": nn.Linear(2, 2)})
    assert "a" in ld


def test_state_dict_roundtrip():
    m1 = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    m2 = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    sd = m1.state_dict()
    assert "0.weight" in sd and "2.bias" in sd
    m2.set_state_dict(sd)
    x = pt.randn([2, 4])
    np.testing.assert_allclose(m1(x).numpy(), m2(x).numpy(), rtol=1e-6)


def test_named_parameters():
    m = nn.Sequential(nn.Linear(4, 8), nn.Linear(8, 2))
    names = [n for n, _ in m.named_parameters()]
    assert set(names) == {"0.weight", "0.bias", "1.weight", "1.bias"}
    assert len(m.parameters()) == 4


def test_mha():
    mha = nn.MultiHeadAttention(32, 4)
    x = pt.randn([2, 10, 32])
    assert mha(x).shape == [2, 10, 32]


def test_transformer_encoder():
    layer = nn.TransformerEncoderLayer(32, 4, 64)
    enc = nn.TransformerEncoder(layer, 2)
    x = pt.randn([2, 6, 32])
    assert enc(x).shape == [2, 6, 32]


def test_lstm_gru():
    lstm = nn.LSTM(8, 16, num_layers=2)
    x = pt.randn([2, 5, 8])
    out, (h, c) = lstm(x)
    assert out.shape == [2, 5, 16]
    assert h.shape == [2, 2, 16]
    gru = nn.GRU(8, 16, direction="bidirectional")
    out, h = gru(x)
    assert out.shape == [2, 5, 32]


def test_losses():
    logits = pt.randn([4, 10]); logits.stop_gradient = False
    labels = pt.to_tensor([1, 2, 3, 4])
    loss = nn.CrossEntropyLoss()(logits, labels)
    assert loss.shape == []
    loss.backward()
    assert logits.grad is not None
    # vs manual log-softmax
    lo = logits.numpy().astype(np.float64)
    ls = lo - np.log(np.exp(lo).sum(-1, keepdims=True))
    expect = -ls[np.arange(4), [1, 2, 3, 4]].mean()
    np.testing.assert_allclose(float(loss), expect, rtol=1e-4)

    assert float(nn.MSELoss()(pt.ones([3]), pt.zeros([3]))) == 1.0
    assert float(nn.L1Loss()(pt.ones([3]) * 2, pt.zeros([3]))) == 2.0


def test_cross_entropy_ignore_index():
    logits = pt.randn([4, 10])
    labels = pt.to_tensor([1, -100, 3, -100])
    loss = F.cross_entropy(logits, labels, ignore_index=-100,
                           reduction="none")
    assert float(loss.numpy()[1]) == 0.0


def test_activations():
    x = pt.to_tensor([-1.0, 0.0, 1.0])
    np.testing.assert_allclose(F.relu(x).numpy(), [0, 0, 1])
    np.testing.assert_allclose(F.sigmoid(x).numpy(),
                               1 / (1 + np.exp([1, 0, -1])), rtol=1e-5)
    assert F.gelu(x).shape == [3]
    assert F.softmax(x).numpy().sum() == pytest.approx(1.0, rel=1e-5)


def test_clip_grad_global_norm():
    clip = nn.ClipGradByGlobalNorm(1.0)
    p1 = pt.parameter([3.0, 4.0])
    from paddle_tpu.tensor import Tensor
    import jax.numpy as jnp
    (clipped,) = clip._clip_arrays([jnp.asarray([3.0, 4.0])])
    np.testing.assert_allclose(np.asarray(clipped), [0.6, 0.8], rtol=1e-5)


def test_initializers():
    from paddle_tpu.nn import initializer as I
    t = pt.parameter(np.zeros((100, 50), np.float32))
    I.XavierUniform()(t)
    limit = np.sqrt(6.0 / 150)
    assert abs(t.numpy()).max() <= limit + 1e-6
    I.Constant(3.0)(t)
    assert (t.numpy() == 3.0).all()
    I.Normal(0, 0.02)(t)
    assert abs(t.numpy().std() - 0.02) < 0.005


def test_sdpa_causal():
    q = pt.randn([1, 4, 2, 8])
    k = pt.randn([1, 4, 2, 8])
    v = pt.randn([1, 4, 2, 8])
    out = F.scaled_dot_product_attention(q, k, v, is_causal=True)
    assert out.shape == [1, 4, 2, 8]
    # first position attends only to itself
    from paddle_tpu.ops.dispatch import call_raw
    import jax.numpy as jnp
    full = call_raw("sdpa", q._array, k._array, v._array, None,
                    is_causal=True)
    np.testing.assert_allclose(np.asarray(full[:, 0]),
                               np.asarray(v._array[:, 0]), rtol=1e-4,
                               atol=1e-5)
