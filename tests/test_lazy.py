"""LazyGuard (deferred init) — reference surface: paddle.LazyGuard.

The contract under test: lazy construction produces BIT-IDENTICAL
parameters to eager construction under the same seed, leaves the global
RNG in the same state, and materializes everything in one jitted program
(framework/lazy.py).
"""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.framework import lazy as _lazy


def _mlp():
    return pt.nn.Sequential(
        pt.nn.Linear(8, 32),
        pt.nn.ReLU(),
        pt.nn.LayerNorm(32),
        pt.nn.Linear(32, 4),
    )


def _params_np(m):
    return [np.asarray(p._array) for p in m.parameters()]


class TestLazyGuard:
    def test_bitwise_equals_eager(self):
        pt.seed(1234)
        with pt.LazyGuard():
            lazy_m = _mlp()
        pt.seed(1234)
        eager_m = _mlp()
        for a, b in zip(_params_np(lazy_m), _params_np(eager_m)):
            np.testing.assert_array_equal(a, b)

    def test_rng_state_continues_like_eager(self):
        # a draw AFTER the guard must match the draw after eager build
        pt.seed(77)
        with pt.LazyGuard():
            _mlp()
        lazy_next = pt.rand([4]).numpy()
        pt.seed(77)
        _mlp()
        eager_next = pt.rand([4]).numpy()
        np.testing.assert_array_equal(lazy_next, eager_next)

    def test_placeholder_has_shape_dtype_before_materialize(self):
        with pt.LazyGuard():
            lin = pt.nn.Linear(3, 5)
            assert lin.weight.shape == [3, 5]
            assert lin.weight.size == 15
        # materialized on exit
        assert lin.weight.numpy().shape == (3, 5)

    def test_exception_drops_pending(self):
        with pytest.raises(RuntimeError):
            with pt.LazyGuard():
                pt.nn.Linear(3, 5)
                raise RuntimeError("construction failed")
        assert not _lazy._STATE["pending"]
        assert not _lazy.active()

    def test_nested_guards_materialize_once_at_outer_exit(self):
        with pt.LazyGuard():
            a = pt.nn.Linear(2, 2)
            with pt.LazyGuard():
                b = pt.nn.Linear(2, 2)
            # inner exit must NOT materialize (outer still open)
            import jax
            assert isinstance(b.weight._array, jax.ShapeDtypeStruct)
        assert a.weight.numpy().shape == (2, 2)
        assert b.weight.numpy().shape == (2, 2)

    def test_gpt_tiny_lazy_forward_parity(self):
        from paddle_tpu.text import GPTConfig, GPTForCausalLM
        cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=2,
                        num_heads=4, intermediate_size=64,
                        max_position_embeddings=16, hidden_dropout=0.0,
                        attention_dropout=0.0)
        pt.seed(5)
        with pt.LazyGuard():
            m1 = GPTForCausalLM(cfg)
        pt.seed(5)
        m2 = GPTForCausalLM(cfg)
        ids = pt.to_tensor(np.arange(16, dtype=np.int64)[None, :] % 64)
        with pt.no_grad():
            o1 = m1(ids).numpy()
            o2 = m2(ids).numpy()
        # jit fuses mul+add (FMA) inside the init program, so values can
        # differ from eager by 1 ulp; the PRNG subkey SEQUENCE is identical
        np.testing.assert_allclose(o1, o2, rtol=2e-4, atol=1e-6)

    def test_deepcopy_cloned_layers_materialize(self):
        # TransformerEncoder clones its prototype layer via copy.deepcopy;
        # the clones' placeholders must materialize as ALIASES (identical
        # values to the source — deepcopy semantics), not fresh draws
        from paddle_tpu.text.bert import BertConfig, BertModel
        import jax
        cfg = BertConfig(vocab_size=64, hidden_size=16, num_hidden_layers=3,
                         num_attention_heads=2, intermediate_size=32,
                         max_position_embeddings=32)
        pt.seed(9)
        with pt.LazyGuard():
            m = BertModel(cfg)
        named = dict(m.named_parameters())
        for n, p in named.items():
            assert not isinstance(p._array, jax.ShapeDtypeStruct), n
        w0 = named["encoder.layers.0.self_attn.q_proj.weight"].numpy()
        w1 = named["encoder.layers.1.self_attn.q_proj.weight"].numpy()
        np.testing.assert_array_equal(w0, w1)

    def test_deepcopy_outside_guard_independent_buffer(self):
        # fused train steps donate param buffers, so a deepcopy must own
        # its storage — sharing would leave the copy pointing at a deleted
        # buffer after the source's first optimizer step
        import copy
        t = pt.to_tensor(np.arange(6.0, dtype=np.float32).reshape(2, 3))
        t2 = copy.deepcopy(t)
        np.testing.assert_array_equal(t.numpy(), t2.numpy())
        assert t2._array is not t._array
        t2._inplace_assign(t2._array + 1.0)
        assert float(t.sum()) == 15.0

    def test_lazy_clone_independent_buffer(self):
        import copy
        with pt.LazyGuard():
            a = pt.nn.Linear(4, 4)
            b = copy.deepcopy(a)
        np.testing.assert_array_equal(a.weight.numpy(), b.weight.numpy())
        assert b.weight._array is not a.weight._array

    def test_lazy_with_tensor_parallel_fleet(self):
        # tp layers create params through Layer.create_parameter, so a
        # LazyGuard build must materialize before pjit shards them
        from paddle_tpu.distributed import fleet, mesh as mesh_mod
        from paddle_tpu.text import GPTConfig, GPTForCausalLM, gpt_loss_fn
        prev = dict(mesh_mod._state)
        try:
            strategy = fleet.DistributedStrategy()
            strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 2,
                                       "pp_degree": 1}
            fleet.init(is_collective=True, strategy=strategy)
            pt.seed(0)
            cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=2,
                            num_heads=4, max_position_embeddings=16,
                            tensor_parallel=True, hidden_dropout=0.0,
                            attention_dropout=0.0)
            with pt.LazyGuard():
                m = GPTForCausalLM(cfg)
            opt = pt.optimizer.AdamW(learning_rate=1e-3,
                                     parameters=m.parameters())
            step = fleet.build_train_step(m, gpt_loss_fn, opt)
            ids = pt.randint(0, 64, [4, 16])
            l0 = float(step(ids, ids))
            for _ in range(4):
                l = float(step(ids, ids))
            assert l < l0
        finally:
            mesh_mod._state.update(prev)

    def test_train_after_lazy_build(self):
        pt.seed(3)
        with pt.LazyGuard():
            m = _mlp()
        opt = pt.optimizer.Adam(learning_rate=1e-2,
                                parameters=m.parameters())
        x = pt.rand([16, 8])
        y = pt.rand([16, 4])
        losses = []
        for _ in range(3):
            loss = ((m(x) - y) ** 2).mean()
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss))
        assert losses[-1] < losses[0]
