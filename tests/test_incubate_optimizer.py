"""incubate LookAhead/ModelAverage + distributed.sharding shim."""
import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.nn as nn
from paddle_tpu.incubate import LookAhead, ModelAverage


def test_lookahead_converges_and_interpolates():
    pt.seed(0)
    w = pt.to_tensor(np.array([4.0, -3.0], np.float32))
    w.stop_gradient = False
    inner = pt.optimizer.SGD(learning_rate=0.2, parameters=[w])
    opt = LookAhead(inner, alpha=0.5, k=3)
    for _ in range(40):
        loss = (w ** 2).sum()
        loss.backward()
        opt.step()
        opt.clear_grad()
    assert float((w ** 2).sum()) < 1e-3
    sd = opt.state_dict()
    assert any(k.startswith("__lookahead__/slow") for k in sd)
    opt2 = LookAhead(pt.optimizer.SGD(learning_rate=0.2, parameters=[w]),
                     alpha=0.5, k=3)
    opt2.set_state_dict(sd)
    assert opt2._steps == opt._steps


def test_lookahead_slow_weight_math():
    """After exactly k fast steps, weights = slow + alpha*(fast - slow)."""
    pt.seed(1)
    w = pt.to_tensor(np.array([1.0], np.float32))
    w.stop_gradient = False
    inner = pt.optimizer.SGD(learning_rate=0.1, parameters=[w])
    opt = LookAhead(inner, alpha=0.5, k=2)
    w0 = w.numpy().copy()
    fast = w0.copy()
    for _ in range(2):   # grad of w^2 is 2w
        fast = fast - 0.1 * 2 * fast
        loss = (w ** 2).sum()
        loss.backward()
        opt.step()
        opt.clear_grad()
    want = w0 + 0.5 * (fast - w0)
    np.testing.assert_allclose(w.numpy(), want, rtol=1e-5)


def test_model_average_apply_restore():
    pt.seed(2)
    w = pt.to_tensor(np.array([10.0], np.float32))
    w.stop_gradient = False
    opt = pt.optimizer.SGD(learning_rate=0.3, parameters=[w])
    ma = ModelAverage(parameters=[w])
    vals = [w.numpy()[0]]
    for _ in range(5):
        loss = (w ** 2).sum()
        loss.backward()
        opt.step(); opt.clear_grad()
        ma.step()
        vals.append(w.numpy()[0])
    cur = w.numpy().copy()
    ma.apply()
    np.testing.assert_allclose(w.numpy(), np.mean(vals), rtol=1e-5)
    ma.restore()
    np.testing.assert_allclose(w.numpy(), cur)
    with pytest.raises(RuntimeError, match="apply"):
        ma.restore()


def test_group_sharded_parallel_configures_fleet():
    from paddle_tpu.distributed import fleet, mesh as mesh_mod
    from paddle_tpu.distributed.sharding import group_sharded_parallel
    prev = dict(mesh_mod._state)
    try:
        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs = {"dp_degree": 4, "mp_degree": 1,
                                   "pp_degree": 1, "sharding_degree": 4}
        fleet.init(is_collective=True, strategy=strategy)
        m = nn.Linear(8, 8)
        opt = pt.optimizer.Adam(learning_rate=0.01,
                                parameters=m.parameters())
        m2, o2, _ = group_sharded_parallel(m, opt, "os_g")
        assert strategy.hybrid_configs["sharding_stage"] == 2
        with pytest.raises(ValueError, match="level"):
            group_sharded_parallel(m, opt, "bogus")
        with pytest.raises(NotImplementedError, match="offload"):
            group_sharded_parallel(m, opt, "os", offload=True)
    finally:
        mesh_mod._state.update(prev)
