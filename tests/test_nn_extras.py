"""Round-2 nn additions (SURVEY §2 nn bullets): CTC vs torch, fold/unfold
round-trip, max_unpool scatter, new losses vs torch, cells/BiRNN."""
import numpy as np
import pytest
import torch

import paddle_tpu as pt
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F


def test_ctc_loss_matches_torch():
    rng = np.random.RandomState(0)
    T, B, C, S = 12, 3, 6, 4
    logits = rng.randn(T, B, C).astype(np.float32)
    labels = rng.randint(1, C, (B, S)).astype(np.int32)
    in_len = np.array([12, 10, 8], np.int32)
    lb_len = np.array([4, 3, 2], np.int32)

    got = F.ctc_loss(pt.to_tensor(logits), pt.to_tensor(labels),
                     pt.to_tensor(in_len), pt.to_tensor(lb_len),
                     blank=0, reduction="none").numpy()
    want = torch.nn.functional.ctc_loss(
        torch.log_softmax(torch.tensor(logits), dim=-1),
        torch.tensor(labels.astype(np.int64)),
        torch.tensor(in_len.astype(np.int64)),
        torch.tensor(lb_len.astype(np.int64)),
        blank=0, reduction="none").numpy()
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def test_ctc_loss_grad_and_layer():
    rng = np.random.RandomState(1)
    logits = pt.to_tensor(rng.randn(8, 2, 5).astype(np.float32))
    logits.stop_gradient = False
    labels = pt.to_tensor(np.array([[1, 2], [3, 4]], np.int32))
    loss = nn.CTCLoss(blank=0)(logits, labels,
                               pt.to_tensor(np.array([8, 8], np.int32)),
                               pt.to_tensor(np.array([2, 2], np.int32)))
    loss.backward()
    g = logits.grad.numpy()
    assert np.isfinite(g).all() and np.abs(g).sum() > 0


def test_fold_unfold_roundtrip():
    """fold(unfold(x)) divides back to x where windows tile exactly."""
    rng = np.random.RandomState(0)
    x = pt.to_tensor(rng.randn(2, 3, 8, 8).astype(np.float32))
    cols = F.unfold(x, 2, strides=2)
    assert cols.shape == [2, 12, 16]
    back = F.fold(cols, (8, 8), 2, strides=2)
    np.testing.assert_allclose(back.numpy(), x.numpy(), rtol=1e-6)


def test_fold_matches_torch_overlapping():
    rng = np.random.RandomState(2)
    cols = rng.randn(2, 3 * 9, 36).astype(np.float32)  # 3x3 kernel on 8x8
    got = F.fold(pt.to_tensor(cols), (8, 8), 3, strides=1,
                 paddings=0).numpy()
    want = torch.nn.functional.fold(torch.tensor(cols), (8, 8), 3).numpy()
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_max_unpool2d_roundtrip():
    rng = np.random.RandomState(3)
    x = pt.to_tensor(rng.randn(2, 3, 8, 8).astype(np.float32))
    pooled, idx = F.max_pool2d(x, 2, return_mask=True)
    up = nn.MaxUnpool2D(2)(pooled, idx)
    assert up.shape == [2, 3, 8, 8]
    # every pooled max lands back at its argmax position
    np.testing.assert_allclose(np.sort(np.abs(up.numpy()).reshape(2, 3, -1))
                               [..., -16:],
                               np.sort(np.abs(pooled.numpy()).reshape(
                                   2, 3, -1)), rtol=1e-6)


@pytest.mark.parametrize("name,args", [
    ("triplet_margin_loss", 3), ("soft_margin_loss", 2),
    ("hinge_embedding_loss", 2), ("multi_label_soft_margin_loss", 2),
])
def test_new_losses_match_torch(name, args):
    rng = np.random.RandomState(4)
    a = rng.randn(6, 10).astype(np.float32)
    b = rng.randn(6, 10).astype(np.float32)
    c = rng.randn(6, 10).astype(np.float32)
    sign = np.where(rng.rand(6, 10) > 0.5, 1.0, -1.0).astype(np.float32)
    binary = (sign > 0).astype(np.float32)
    if name == "triplet_margin_loss":
        got = F.triplet_margin_loss(pt.to_tensor(a), pt.to_tensor(b),
                                    pt.to_tensor(c)).numpy()
        want = torch.nn.functional.triplet_margin_loss(
            torch.tensor(a), torch.tensor(b), torch.tensor(c)).numpy()
        rtol = 1e-4
    elif name == "soft_margin_loss":
        got = F.soft_margin_loss(pt.to_tensor(a),
                                 pt.to_tensor(sign)).numpy()
        want = torch.nn.functional.soft_margin_loss(
            torch.tensor(a), torch.tensor(sign)).numpy()
        rtol = 1e-5
    elif name == "hinge_embedding_loss":
        got = F.hinge_embedding_loss(pt.to_tensor(a),
                                     pt.to_tensor(sign)).numpy()
        want = torch.nn.functional.hinge_embedding_loss(
            torch.tensor(a), torch.tensor(sign)).numpy()
        rtol = 1e-5
    else:
        got = F.multi_label_soft_margin_loss(pt.to_tensor(a),
                                             pt.to_tensor(binary)).numpy()
        want = torch.nn.functional.multilabel_soft_margin_loss(
            torch.tensor(a), torch.tensor(binary)).numpy()
        rtol = 1e-5
    np.testing.assert_allclose(got, want, rtol=rtol, atol=1e-5)


def test_gaussian_and_poisson_nll():
    rng = np.random.RandomState(5)
    x = rng.randn(4, 3).astype(np.float32)
    y = rng.randn(4, 3).astype(np.float32)
    var = np.abs(rng.randn(4, 3)).astype(np.float32) + 0.1
    got = F.gaussian_nll_loss(pt.to_tensor(x), pt.to_tensor(y),
                              pt.to_tensor(var)).numpy()
    want = torch.nn.functional.gaussian_nll_loss(
        torch.tensor(x), torch.tensor(y), torch.tensor(var)).numpy()
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)

    rate = np.abs(rng.randn(4, 3)).astype(np.float32)
    tgt = rng.poisson(2.0, (4, 3)).astype(np.float32)
    got = F.poisson_nll_loss(pt.to_tensor(rate), pt.to_tensor(tgt)).numpy()
    want = torch.nn.functional.poisson_nll_loss(
        torch.tensor(rate), torch.tensor(tgt)).numpy()
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_pairwise_distance_and_layers():
    rng = np.random.RandomState(6)
    a = rng.randn(5, 8).astype(np.float32)
    b = rng.randn(5, 8).astype(np.float32)
    got = nn.PairwiseDistance()(pt.to_tensor(a), pt.to_tensor(b)).numpy()
    want = torch.nn.functional.pairwise_distance(
        torch.tensor(a), torch.tensor(b)).numpy()
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)

    x = pt.to_tensor(rng.randn(2, 8, 4, 4).astype(np.float32))
    cs = nn.ChannelShuffle(4)(x)
    assert cs.shape == [2, 8, 4, 4]
    pu = nn.PixelUnshuffle(2)(x)
    assert pu.shape == [2, 32, 2, 2]
    # pixel_unshuffle inverts pixel_shuffle
    ps = F.pixel_shuffle(pu, 2)
    np.testing.assert_allclose(ps.numpy(), x.numpy(), rtol=1e-6)
    sm = nn.Softmax2D()(x)
    np.testing.assert_allclose(sm.numpy().sum(axis=1),
                               np.ones((2, 4, 4)), rtol=1e-5)
    zp = nn.ZeroPad2D(1)(x)
    assert zp.shape == [2, 8, 6, 6]


def test_simple_rnn_cell_and_birnn():
    pt.seed(0)
    cell_f = nn.SimpleRNNCell(4, 8)
    cell_b = nn.SimpleRNNCell(4, 8)
    x = pt.randn([2, 5, 4])
    out, (sf, sb) = nn.BiRNN(cell_f, cell_b)(x)
    assert out.shape == [2, 5, 16]
    assert sf.shape == [2, 8] and sb.shape == [2, 8]
    loss = out.mean()
    loss.backward()
    assert cell_f.weight_ih.grad is not None
    assert cell_b.weight_hh.grad is not None


def test_set_state_dict_accepts_torch_tensors():
    # interop path: HF converters hand over torch CPU tensors; the batched
    # cast in Layer.set_state_dict must coerce non-jax array-likes
    import numpy as np
    import torch
    import paddle_tpu as pt
    lin = pt.nn.Linear(3, 2)
    w = torch.arange(6, dtype=torch.float32).reshape(3, 2)
    b = torch.zeros(2)
    missing, unexpected = lin.set_state_dict({"weight": w, "bias": b})
    assert not missing and not unexpected
    np.testing.assert_allclose(lin.weight.numpy(), w.numpy())
