"""Round-2 feature tests: amp custom lists, optimizer param groups,
check_numerics failure detection."""
import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F


# --------------------------------------------------------------------- amp
def test_amp_custom_white_list_casts_kept_op():
    """An op with default policy "keep" casts to bf16 when white-listed."""
    x = pt.ones([4, 4], dtype="float32")
    with pt.amp.auto_cast(level="O1", dtype="bfloat16",
                          custom_white_list=["add"]):
        y = x + x
    assert str(y.dtype) in ("paddle.bfloat16", "bfloat16") or \
        "bfloat16" in str(y.dtype)


def test_amp_custom_black_list_keeps_fp32():
    """matmul (default "allow") stays fp32 when black-listed."""
    a = pt.ones([4, 4], dtype="float32")
    b = pt.ones([4, 4], dtype="float32")
    with pt.amp.auto_cast(level="O1", dtype="bfloat16",
                          custom_black_list=["matmul"]):
        y = a.matmul(b)
    assert "float32" in str(y.dtype)
    with pt.amp.auto_cast(level="O1", dtype="bfloat16"):
        y2 = a.matmul(b)
    assert "bfloat16" in str(y2.dtype)


def test_amp_black_wins_over_white():
    a = pt.ones([4, 4], dtype="float32")
    with pt.amp.auto_cast(level="O1", dtype="bfloat16",
                          custom_white_list=["matmul"],
                          custom_black_list=["matmul"]):
        y = a.matmul(a)
    assert "float32" in str(y.dtype)


# ------------------------------------------------------------ param groups
def test_optimizer_param_groups_lr_scale():
    """Group learning_rate is a coefficient on the global lr."""
    pt.seed(0)
    a = pt.create_parameter([4], "float32")
    b = pt.create_parameter([4], "float32")
    a.set_value(pt.ones([4])); b.set_value(pt.ones([4]))
    opt = pt.optimizer.SGD(learning_rate=0.1, parameters=[
        {"params": [a]},
        {"params": [b], "learning_rate": 0.1},  # 10x smaller effective lr
    ])
    ga = pt.ones([4]); gb = pt.ones([4])
    a.grad = ga; b.grad = gb
    opt.step()
    np.testing.assert_allclose(a.numpy(), 0.9 * np.ones(4), rtol=1e-6)
    np.testing.assert_allclose(b.numpy(), 0.99 * np.ones(4), rtol=1e-6)


def test_optimizer_param_groups_weight_decay_override():
    """Group weight_decay overrides the global coefficient (AdamW)."""
    pt.seed(0)
    a = pt.create_parameter([4], "float32")
    b = pt.create_parameter([4], "float32")
    opt = pt.optimizer.AdamW(learning_rate=0.1, weight_decay=0.5,
                             parameters=[
                                 {"params": [a]},
                                 {"params": [b], "weight_decay": 0.0},
                             ])
    a.set_value(pt.ones([4])); b.set_value(pt.ones([4]))
    a.grad = pt.zeros([4]); b.grad = pt.zeros([4])
    opt.step()
    assert float(a.numpy()[0]) < 1.0          # decayed
    np.testing.assert_allclose(b.numpy(), np.ones(4), atol=1e-7)  # not


def test_param_groups_in_fused_train_step():
    """Param groups survive the fused TrainStep path."""
    pt.seed(2)
    m = nn.Linear(4, 4)
    opt = pt.optimizer.SGD(learning_rate=0.1, parameters=[
        {"params": [m.weight]},
        {"params": [m.bias], "learning_rate": 0.0},  # frozen bias
    ])
    bias_before = m.bias.numpy().copy()
    step = pt.jit.train_step(m, lambda mm, x, y: F.mse_loss(mm(x), y), opt)
    x = pt.randn([8, 4]); y = pt.randn([8, 4])
    for _ in range(2):
        step(x, y)
    np.testing.assert_allclose(m.bias.numpy(), bias_before, atol=1e-7)
    assert not np.allclose(m.weight.numpy(),
                           m.weight.numpy() * 0 + m.weight.numpy()[0, 0])


# ---------------------------------------------------- trace-safety guards
def test_to_static_data_dependent_branch_raises():
    class Branchy(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc = nn.Linear(4, 4)

        def forward(self, x):
            y = self.fc(x)
            if y.sum() > 0:  # data-dependent python branch
                return y
            return -y

    m = pt.jit.to_static(Branchy())
    with pytest.raises(RuntimeError, match="to_static"):
        m(pt.randn([2, 4]))


def test_int64_requests_resolve_to_int32_without_warning():
    import warnings
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        a = pt.arange(0, 5, dtype="int64")
        r = pt.randint(0, 5, [3])
    assert "int32" in str(a.dtype) and "int32" in str(r.dtype)


# ---------------------------------------------------------- check_numerics
def test_check_numerics_raises_on_nan_loss():
    from paddle_tpu.framework import flags
    pt.seed(3)
    m = nn.Linear(4, 4)
    opt = pt.optimizer.SGD(learning_rate=0.1, parameters=m.parameters())

    def bad_loss(mm, x):
        out = mm(x)
        return (out.sum() - out.sum()) / (out.sum() - out.sum())  # nan

    flags.set_flags({"check_numerics": True})
    try:
        step = pt.jit.train_step(m, bad_loss, opt)
        with pytest.raises(FloatingPointError, match="check_numerics"):
            step(pt.randn([2, 4]))
    finally:
        flags.set_flags({"check_numerics": False})


def test_check_numerics_clean_run_passes():
    from paddle_tpu.framework import flags
    pt.seed(4)
    m = nn.Linear(4, 4)
    opt = pt.optimizer.SGD(learning_rate=0.1, parameters=m.parameters())
    flags.set_flags({"check_numerics": True})
    try:
        step = pt.jit.train_step(
            m, lambda mm, x, y: F.mse_loss(mm(x), y), opt)
        loss = step(pt.randn([2, 4]), pt.randn([2, 4]))
        assert np.isfinite(float(loss))
    finally:
        flags.set_flags({"check_numerics": False})


def test_check_numerics_eager_api():
    from paddle_tpu.framework import flags, debugging
    flags.set_flags({"check_numerics": True})
    try:
        debugging.check_numerics(pt.ones([3]), "ok")  # no raise
        bad = pt.ones([3]) / pt.zeros([3])
        with pytest.raises(FloatingPointError):
            debugging.check_numerics(bad, "bad")
    finally:
        flags.set_flags({"check_numerics": False})


# ----------------------------------------------------------- paddle_tpu.utils
def test_utils_surface():
    from paddle_tpu import utils
    x = pt.randn([4, 8]); y = pt.randn([4, 8])
    c = utils.cosine_similarity(x, y, axis=1)
    assert c.shape == [4] or tuple(c.shape) == (4,)
    cs = utils.CosineSimilarity(axis=1)(x, y)
    np.testing.assert_allclose(c.numpy(), cs.numpy())
    r = utils.rearrange(x, "b (h w) -> b h w", h=2)
    assert tuple(r.shape) == (4, 2, 4)
    assert utils.unique_name.generate("fc") == "fc_0"
    assert utils.unique_name.generate("fc") == "fc_1"
    clipped = utils.clip(pt.ones([3]) * 5.0, max=1.0)
    np.testing.assert_allclose(clipped.numpy(), np.ones(3))


def test_utils_clip_grad_norm():
    from paddle_tpu import utils
    p = pt.create_parameter([4], "float32")
    p.grad = pt.ones([4]) * 10.0
    total = utils.clip_grad_norm_([p], max_norm=1.0)
    assert float(total) > 1.0
    np.testing.assert_allclose(
        np.linalg.norm(p.grad.numpy()), 1.0, rtol=1e-4)


# -------------------------------------------------------------- beam search
def test_beam_search_gpt():
    from paddle_tpu.text import GPTConfig, GPTForCausalLM, beam_search
    pt.seed(21)
    cfg = GPTConfig(vocab_size=32, hidden_size=16, num_layers=2, num_heads=2,
                    max_position_embeddings=64, hidden_dropout=0.0,
                    attention_dropout=0.0, tensor_parallel=False)
    m = GPTForCausalLM(cfg)
    ids = pt.randint(0, 32, [2, 4])
    out = beam_search(m, ids, beam_size=3, max_new_tokens=5)
    assert tuple(out.shape) == (2, 9)
    # beam=1 must agree with greedy decode
    b1 = beam_search(m, ids, beam_size=1, max_new_tokens=5)
    greedy = m.generate(ids, max_new_tokens=5, use_jit=False)
    np.testing.assert_array_equal(b1.numpy(), greedy.numpy())


# ------------------------------------------------------- ernie inference demo
def test_ernie_fused_inference_roundtrip(tmp_path):
    """BASELINE config 5: ERNIE-3.0 inference via to_static → save_inference
    → load_inference (the dy2static + CINN fused-graph analog)."""
    from paddle_tpu.text import ErnieConfig, ErnieForSequenceClassification
    from paddle_tpu.jit.save_load import (save_inference, load_inference,
                                          InputSpec)
    pt.seed(22)
    cfg = ErnieConfig(vocab_size=64, hidden_size=32, num_hidden_layers=2,
                      num_attention_heads=4, intermediate_size=64,
                      max_position_embeddings=32)
    m = ErnieForSequenceClassification(cfg, num_classes=3)
    m.eval()
    ids = pt.randint(0, 64, [2, 8])
    eager = m(ids)
    static = pt.jit.to_static(m)
    fused = static(ids)
    np.testing.assert_allclose(eager.numpy(), fused.numpy(), rtol=1e-4,
                               atol=1e-5)
    path = str(tmp_path / "ernie_infer")
    save_inference(m, path, [InputSpec([2, 8], "int32")])
    loaded = load_inference(path)
    out = loaded(ids)
    got = out[0] if isinstance(out, (list, tuple)) else out
    np.testing.assert_allclose(eager.numpy(), got.numpy(), rtol=1e-4,
                               atol=1e-5)


# --------------------------------------------------- async device buffering
def test_dataloader_buffer_reader_values_and_lookahead(monkeypatch):
    """use_buffer_reader stages batches ahead of consumption (async H2D
    overlap) without changing values or order."""
    import paddle_tpu.io as io

    xs = np.arange(32, dtype=np.float32).reshape(8, 4)
    ys = np.arange(8, dtype=np.float32)

    class DS(io.Dataset):
        def __len__(self):
            return 8

        def __getitem__(self, i):
            return xs[i], ys[i]

    staged = []
    orig = io._stage_to_device

    def tracking_stage(b):
        staged.append(1)
        return orig(b)

    monkeypatch.setattr(io, "_stage_to_device", tracking_stage)
    dl = io.DataLoader(DS(), batch_size=2, shuffle=False,
                       use_buffer_reader=True, prefetch_factor=2)
    it = iter(dl)
    first = next(it)
    # double-buffer: by the time batch 0 is handed out, batch 1 (at least)
    # has already been staged to device
    assert len(staged) >= 2
    np.testing.assert_allclose(first[0].numpy(), xs[:2])
    rest = list(it)
    got = np.concatenate([first[0].numpy()] + [b[0].numpy() for b in rest])
    np.testing.assert_allclose(got, xs)

    # plain path unchanged
    dl2 = io.DataLoader(DS(), batch_size=2, use_buffer_reader=False)
    b0 = next(iter(dl2))
    np.testing.assert_allclose(b0[0].numpy(), xs[:2])


def test_flops_counts_real_hlo():
    """paddle.flops via XLA cost analysis: a Linear(8->4) on batch 2 is
    2*2*8*4 = 128 matmul flops + 2*4 bias adds = 136."""
    import paddle_tpu as pt
    import paddle_tpu.nn as nn
    pt.seed(0)
    m = nn.Linear(8, 4)
    f = pt.flops(m, [2, 8])
    assert f == 136
    m2 = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
    f2 = pt.flops(m2, [2, 8], print_detail=True)
    assert f2 >= 2 * 2 * (8 * 16 + 16 * 4)


def test_profiler_events_scheduler_and_program_stats(tmp_path):
    import time as _time
    import jax.numpy as jnp
    import paddle_tpu as pt
    from paddle_tpu import profiler as prof
    prof.reset_events()
    p = prof.Profiler(timer_only=True, scheduler=prof.make_scheduler(
        skip_first=1, record=2))
    p.start()
    for i in range(4):
        with prof.RecordEvent("work"):
            _time.sleep(0.002)
        p.step(num_samples=8)
    p.stop()
    s = p.summary()
    assert "steps=4" in s and "throughput=" in s
    assert "work" in s and "      4" in s  # event count aggregated

    stats = prof.program_stats(lambda a, b: a @ b,
                               jnp.ones((8, 16)), jnp.ones((16, 4)))
    assert stats["flops"] == 1024.0


def test_flops_preserves_training_mode():
    import paddle_tpu as pt
    import paddle_tpu.nn as nn
    m = nn.Sequential(nn.Linear(4, 4), nn.Dropout(0.5))
    m.train()
    pt.flops(m, [2, 4])
    assert m.training and m[1].training  # eval() side effect restored


def test_profiler_restart_resets():
    import paddle_tpu.profiler as prof
    p = prof.Profiler(timer_only=True)
    p.start()
    p.step(); p.step()
    p.stop()
    p.start()
    p.step()
    p.stop()
    assert "steps=1" in p.summary()


def test_param_attr_initializer_trainable_and_lr():
    import numpy as np
    import paddle_tpu as pt
    import paddle_tpu.nn as nn
    pt.seed(0)
    lin = nn.Linear(
        4, 4,
        weight_attr=pt.ParamAttr(
            name="my_w", initializer=nn.initializer.Constant(0.5),
            learning_rate=0.1),
        bias_attr=pt.ParamAttr(trainable=False))
    np.testing.assert_allclose(lin.weight.numpy(), 0.5)
    assert lin.weight.name == "my_w"
    assert lin.bias.stop_gradient  # frozen by trainable=False
    assert lin.weight.optimize_attr == {"learning_rate": 0.1}

    # the per-param lr coefficient reaches the optimizer scales
    opt = pt.optimizer.SGD(learning_rate=1.0,
                           parameters=[lin.weight])
    x = pt.ones([2, 4]); y = pt.zeros([2, 4])
    import paddle_tpu.nn.functional as F
    loss = F.mse_loss(lin(x), y)
    loss.backward()
    g = lin.weight.grad.numpy().copy()
    w0 = lin.weight.numpy().copy()
    opt.step()
    np.testing.assert_allclose(lin.weight.numpy(), w0 - 0.1 * g, rtol=1e-5)


def test_param_attr_review_regressions():
    """Frozen params stay in state_dict; conv/norm honor ParamAttr;
    per-param regularizer feeds decay; need_clip exempts from clipping;
    L1Decay raises loudly."""
    import numpy as np
    import paddle_tpu as pt
    import paddle_tpu.nn as nn
    pt.seed(0)
    # frozen param remains a registered parameter
    lin = nn.Linear(4, 4, bias_attr=pt.ParamAttr(trainable=False))
    assert "bias" in dict(lin.named_parameters())
    assert "bias" in lin.state_dict()

    # conv + norm honor trainable/lr
    conv = nn.Conv2D(3, 8, 3, weight_attr=pt.ParamAttr(
        learning_rate=0.5, trainable=False))
    assert conv.weight.stop_gradient
    assert conv.weight.optimize_attr["learning_rate"] == 0.5
    ln = nn.LayerNorm(8, weight_attr=pt.ParamAttr(trainable=False))
    assert ln.weight.stop_gradient
    bn = nn.BatchNorm2D(4, weight_attr=pt.ParamAttr(trainable=False))
    assert bn.weight.stop_gradient

    # per-param regularizer overrides global decay
    w = nn.Linear(4, 4, weight_attr=pt.ParamAttr(
        regularizer=pt.regularizer.L2Decay(0.7)))
    opt = pt.optimizer.AdamW(learning_rate=0.1, weight_decay=0.0,
                             parameters=w.parameters())
    assert 0.7 in opt._wd_overrides

    # need_clip=False exempts from clipping
    a = pt.parameter(np.ones((2,), np.float32))
    b = pt.parameter(np.ones((2,), np.float32))
    b.optimize_attr = {"need_clip": False}
    opt2 = pt.optimizer.SGD(learning_rate=1.0, parameters=[a, b],
                            grad_clip=pt.nn.ClipGradByGlobalNorm(0.1))
    import jax.numpy as jnp
    g = [jnp.ones((2,)) * 10, jnp.ones((2,)) * 10]
    out = opt2._clip_grad_arrays(g)
    assert float(jnp.abs(out[0]).max()) < 1.0   # clipped
    assert float(jnp.abs(out[1]).max()) == 10.0  # exempt

    with pytest.raises(NotImplementedError):
        pt.optimizer.SGD(learning_rate=0.1,
                         weight_decay=pt.regularizer.L1Decay(0.1),
                         parameters=[a])


def test_frozen_param_not_updated_by_fused_and_fleet_steps():
    """stop_gradient params are registered but must stay bit-exact through
    the fused TrainStep AND the fleet engine."""
    import numpy as np
    import paddle_tpu as pt
    import paddle_tpu.nn as nn
    import paddle_tpu.nn.functional as F
    from paddle_tpu.distributed import fleet, mesh as mesh_mod

    pt.seed(0)
    m = nn.Sequential(
        nn.Linear(8, 16, weight_attr=pt.ParamAttr(trainable=False)),
        nn.Tanh(), nn.Linear(16, 8))
    frozen0 = m[0].weight.numpy().copy()
    assert "0.weight" in dict(m.named_parameters())
    opt = pt.optimizer.Adam(learning_rate=0.05, parameters=m.parameters())
    step = pt.jit.train_step(m, lambda mm, a, b: F.mse_loss(mm(a), b), opt)
    x = pt.randn([8, 8]); y = pt.randn([8, 8])
    l0 = float(step(x, y)); l1 = float(step(x, y))
    assert l1 < l0
    np.testing.assert_array_equal(m[0].weight.numpy(), frozen0)

    prev = dict(mesh_mod._state)
    try:
        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs = {"dp_degree": 4, "mp_degree": 1,
                                   "pp_degree": 1, "sharding_degree": 4,
                                   "sharding_stage": 2}
        fleet.init(is_collective=True, strategy=strategy)
        pt.seed(1)
        m2 = nn.Sequential(
            nn.Linear(8, 16, weight_attr=pt.ParamAttr(trainable=False)),
            nn.Tanh(), nn.Linear(16, 8))
        frozen2 = m2[0].weight.numpy().copy()
        opt2 = pt.optimizer.Adam(learning_rate=0.05,
                                 parameters=m2.parameters())
        fstep = fleet.build_train_step(
            m2, lambda mm, a, b: F.mse_loss(mm(a), b), opt2)
        f0 = float(fstep(x, y)); f1 = float(fstep(x, y))
        assert f1 < f0
        np.testing.assert_array_equal(m2[0].weight.numpy(), frozen2)
    finally:
        mesh_mod._state.update(prev)
