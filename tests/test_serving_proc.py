"""Process-per-replica serving: the framed transport, the worker
process lifecycle, and the kill -9 survival drill.

Load-bearing properties:

* **framing is structural** — frames survive arbitrary wire splits
  (seeded random split points), while torn final frames, oversized
  frames and garbage payloads are REJECTED (FrameError), never
  silently skipped: a dropped frame must become an eviction+failover,
  not a token gap;
* **the transport cannot wedge the router** — blocking reads run under
  the PR-6-shaped TransportPolicy (timeout x retries x backoff), every
  expired attempt counted;
* **cross-process parity** — a stream served by a worker PROCESS
  (including a failover-style ``resume_tokens`` continuation, greedy
  AND sampled) is byte-identical to the in-process engine and the
  sequential reference;
* **no orphans** — close() reports leaks over the wire then reaps;
  abort() TERM→KILLs even a worker that ignores SIGTERM (the wedged-
  in-native-code case).

Tier-1 wiring of ``chaos_check --router --proc`` (real SIGKILL drill)
lives here too, under a wall-clock budget guard.
"""
import io
import os
import signal
import socket
import struct
import time

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.distributed.launch.heartbeat import BeatWatch
from paddle_tpu.observability import metrics
from paddle_tpu.resilience import chaos
from paddle_tpu.serving import ShedRequest
from paddle_tpu.serving import worker as sw
from paddle_tpu.serving.transport import (MAX_FRAME, Channel,
                                          ChannelClosed, FrameDecoder,
                                          FrameError, TransportPolicy,
                                          TransportTimeout, encode)
from paddle_tpu.text import GPTConfig, GPTForCausalLM
from paddle_tpu.text.generation import generate

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

CFG_KW = dict(vocab_size=64, hidden_size=32, num_layers=2, num_heads=4,
              max_position_embeddings=64, hidden_dropout=0.0,
              attention_dropout=0.0, tensor_parallel=False)
ENG_KW = dict(num_blocks=24, block_size=4, max_running=8,
              prefill_chunk=16)


# ===================================================================
# framing: property tests over the pure decoder (no sockets)
# ===================================================================
def _sample_messages(rng, n=40):
    """A realistic interleaving: stream events, step summaries, and a
    few replies mixed in (replies interleave with events on the real
    wire, and order must survive)."""
    out = []
    for i in range(n):
        k = rng.randint(4)
        if k == 0:
            out.append({"ev": "tok", "rid": int(rng.randint(8)),
                        "tok": int(rng.randint(50304))})
        elif k == 1:
            out.append({"ev": "fin", "rid": int(rng.randint(8)),
                        "reason": "eos"})
        elif k == 2:
            out.append({"ev": "step",
                        "summary": {"decoded": int(rng.randint(8)),
                                    "admitted": 0},
                        "gauges": [int(rng.randint(9)), 0, 24]})
        else:
            out.append({"reply": "add_request", "rid": i, "ok": True,
                        "gauges": [0, 1, 23]})
    return out


@pytest.mark.parametrize("seed", [0, 1, 2, 3])
def test_framing_roundtrip_random_split_points(seed):
    rng = np.random.RandomState(seed)
    msgs = _sample_messages(rng)
    blob = b"".join(encode(m) for m in msgs)
    dec = FrameDecoder()
    got = []
    i = 0
    while i < len(blob):
        j = i + int(rng.randint(1, 9))   # partial reads, torn anywhere
        got.extend(dec.feed(blob[i:j]))
        i = j
    assert got == msgs
    dec.close()                          # clean EOF at a frame boundary
    assert dec.pending == 0


def test_framing_torn_final_frame_rejected():
    msgs = _sample_messages(np.random.RandomState(7), n=5)
    blob = b"".join(encode(m) for m in msgs)
    dec = FrameDecoder()
    got = dec.feed(blob[:-3])            # EOF lands mid-final-frame
    assert got == msgs[:-1]
    with pytest.raises(FrameError, match="torn"):
        dec.close()


def test_framing_oversized_frame_rejected_both_sides():
    dec = FrameDecoder(max_frame=64)
    with pytest.raises(FrameError, match="oversized"):
        dec.feed(struct.pack("!I", 65))  # header alone convicts it
    with pytest.raises(FrameError, match="too large"):
        encode({"pad": "x" * 128}, max_frame=64)
    # default bound is sane
    assert MAX_FRAME >= 1 << 20


def test_framing_garbage_payload_rejected():
    dec = FrameDecoder()
    with pytest.raises(FrameError, match="undecodable"):
        dec.feed(struct.pack("!I", 4) + b"\xff\xfe\x00\x01")


def test_channel_preserves_event_reply_interleaving():
    a, b = socket.socketpair()
    parent, worker = Channel(a, "parent"), Channel(b, "worker")
    seq = [{"ev": "tok", "rid": 0, "tok": 1},
           {"reply": "add_request", "rid": 1, "ok": True},
           {"ev": "tok", "rid": 0, "tok": 2},
           {"ev": "fin", "rid": 0, "reason": "length"}]
    for m in seq:
        worker.send(m)
    got = [parent.recv(timeout=5.0) for _ in seq]
    assert got == seq
    assert parent.poll() is None         # drained, no EOF yet
    worker.close()
    with pytest.raises(ChannelClosed):
        parent.recv(timeout=5.0)
    parent.close()


def test_channel_chaos_transport_drop_site():
    a, b = socket.socketpair()
    parent, worker = Channel(a, "r9"), Channel(b, "w")
    for i in range(3):
        worker.send({"ev": "tok", "rid": 0, "tok": i})
    with chaos.scoped("serving.transport_drop@2#r9"):
        assert parent.poll() == {"ev": "tok", "rid": 0, "tok": 0}
        with pytest.raises(FrameError, match="transport_drop"):
            parent.poll()                # frame 2 dropped in transit
    parent.close()
    worker.close()


# ===================================================================
# transport policy: a silent peer costs timeouts, never a wedge
# ===================================================================
class _SilentProc:
    """A 'worker' that is alive but never answers."""
    pid = 0

    @staticmethod
    def poll():
        return None


def test_rpc_timeout_policy_counts_and_raises():
    reg = metrics.registry()
    base = reg.counter("router_transport_timeouts_total").value
    a, b = socket.socketpair()
    pr = object.__new__(sw.ProcReplica)
    pr.name = "silent"
    pr.ch = Channel(a, "silent")
    pr.proc = _SilentProc()
    pr.policy = TransportPolicy(timeout=0.05, retries=1,
                                backoff_base=0.0)
    pr._pending_reply = None
    pr._reqs = {}
    pr._gauges = (0, 0, 0)
    pr._summary = None
    pr._exit_noted = False
    t0 = time.monotonic()
    with pytest.raises(TransportTimeout, match="no reply"):
        pr._rpc("metrics_snapshot")
    # two attempts (timeout x (retries+1)), each counted; and the wait
    # actually returned instead of wedging
    assert reg.counter("router_transport_timeouts_total").value \
        - base == 2
    assert time.monotonic() - t0 < 5.0
    pr.ch.close()
    b.close()


def test_raise_remote_rebuilds_structured_shed():
    with pytest.raises(ShedRequest) as ei:
        sw._raise_remote({"kind": "ShedRequest", "reason": "queue_depth",
                          "detail": {"queue_depth": 5, "watermark": 2}})
    assert ei.value.reason == "queue_depth"
    assert ei.value.detail["queue_depth"] == 5
    with pytest.raises(ValueError, match="nothing left"):
        sw._raise_remote({"kind": "ValueError",
                          "message": "nothing left to generate"})


# ===================================================================
# BeatWatch spawn grace: a worker importing/compiling for longer than
# the heartbeat timeout must not be evicted before its FIRST beat
# ===================================================================
def test_beatwatch_spawn_grace(tmp_path):
    clock = {"t": 100.0}
    path = str(tmp_path / "hb")
    w = BeatWatch(path, timeout=5.0, grace=30.0,
                  clock=lambda: clock["t"])
    # missing file: past the plain timeout but inside the grace window
    clock["t"] += 20.0
    assert not w.stale()
    # grace exhausted without a single beat: genuinely hung startup
    clock["t"] += 11.0
    assert w.stale()
    # first beat observed -> grace disarms, plain timeout from then on
    with open(path, "w"):
        pass
    assert not w.stale()
    clock["t"] += 6.0
    assert w.stale()                 # 6s silence > 5s timeout: no more
    #                                  grace once the worker has beaten
    # default grace is the timeout itself (in-process behavior intact)
    w2 = BeatWatch(str(tmp_path / "hb2"), timeout=5.0,
                   clock=lambda: clock["t"])
    assert w2.grace == 5.0


def test_beatwatch_respawn_leftover_file_keeps_grace(tmp_path):
    """A RESPAWNED slot reuses its hb path — the dead predecessor's
    leftover file is the fresh watch's baseline, NOT a beat, so the
    new worker still gets the full grace window before its first
    beat (the regression: leftover mtime disarmed grace, and a slow
    respawn was hang-evicted into the crash-loop detector)."""
    clock = {"t": 50.0}
    path = str(tmp_path / "hb")
    with open(path, "w"):
        pass                       # the dead worker's leftover beat
    w = BeatWatch(path, timeout=5.0, grace=30.0,
                  clock=lambda: clock["t"])
    clock["t"] += 20.0             # past timeout, inside grace — the
    assert not w.stale()           # leftover file must not count
    os.utime(path, (1, 99999))     # the NEW worker's first real beat
    assert not w.stale()
    clock["t"] += 6.0              # grace disarmed only now
    assert w.stale()


# ===================================================================
# cross-process parity (one worker serves all the parity cases)
# ===================================================================
@pytest.fixture(scope="module")
def gpt():
    pt.seed(0)
    return GPTForCausalLM(GPTConfig(**CFG_KW))


@pytest.fixture(scope="module")
def proc_replica(tmp_path_factory):
    spec = sw.gpt_spec(config=CFG_KW, seed=0, engine=ENG_KW)
    hb = str(tmp_path_factory.mktemp("hb") / "hb.w0")
    h = sw.ProcReplica(spec, "w0", hb,
                       policy=TransportPolicy(timeout=120.0, retries=0))
    assert h.wait_ready(timeout=300.0)
    yield h
    h.abort()        # safety net; the close test already reaped it


def _seq_ref(model, prompt, n):
    out = generate(model, pt.to_tensor(np.asarray([prompt], "int64")),
                   max_new_tokens=n)
    return out.numpy()[0, len(prompt):].tolist()


def _drive(handle, *reqs, budget_s=120.0):
    t0 = time.monotonic()
    while any(r.finish_reason is None for r in reqs):
        assert time.monotonic() - t0 < budget_s, "worker stalled"
        handle.step()
        time.sleep(0.002)


def test_cross_process_greedy_and_resume_parity(gpt, proc_replica):
    prompt = [7, 3, 9, 1, 5]
    ref = _seq_ref(gpt, prompt, 8)
    toks = []
    rq = proc_replica.add_request(
        prompt, max_new_tokens=8,
        on_token=lambda r, t: toks.append(t))
    _drive(proc_replica, rq)
    assert rq.generated == ref == toks
    assert rq.finish_reason == "length"
    # failover-style continuation: seed half the stream, the worker
    # re-prefills and continues — `generated` holds the ABSOLUTE stream
    rq2 = proc_replica.add_request(prompt, max_new_tokens=8,
                                   resume_tokens=ref[:3])
    _drive(proc_replica, rq2)
    assert rq2.generated == ref


def test_cross_process_sampled_resume_parity(gpt, proc_replica):
    from paddle_tpu.serving import LLMEngine
    prompt = [11, 4, 2, 8]
    kw = dict(max_new_tokens=8, do_sample=True, temperature=0.9,
              top_k=20, seed=42)
    # in-process reference on weight-identical model (same seed/config)
    eng = LLMEngine(gpt, **ENG_KW)
    local = eng.add_request(prompt, **kw)
    eng.run()
    rq = proc_replica.add_request(prompt, **kw)
    _drive(proc_replica, rq)
    assert rq.generated == local.generated
    # resume-exactness survives the process boundary: per-(seed,
    # position) draws re-derive the same stream
    rq2 = proc_replica.add_request(prompt,
                                   resume_tokens=local.generated[:4],
                                   **kw)
    _drive(proc_replica, rq2)
    assert rq2.generated == local.generated
    eng.close()


def test_cross_process_validation_error_rebuilt(proc_replica):
    with pytest.raises(ValueError, match="nothing left"):
        proc_replica.add_request([1, 2, 3], max_new_tokens=4,
                                 resume_tokens=[5, 6, 7, 8])


def test_worker_metrics_snapshot_rpc(proc_replica):
    snap = proc_replica.metrics_snapshot()
    names = {rec["name"] for rec in snap}
    assert "serving_tokens_generated_total" in names
    tok = sum(rec.get("value", 0) for rec in snap
              if rec["name"] == "serving_tokens_generated_total")
    assert tok >= 8       # the parity streams above ran in THIS worker


def test_worker_close_reports_leaks_and_reaps(proc_replica):
    pid = proc_replica.proc.pid
    leaks = proc_replica.close()
    assert leaks == ([], [])          # leak report crossed the wire
    with pytest.raises(ProcessLookupError):
        os.kill(pid, 0)               # dead AND reaped — no orphan


def test_wedged_worker_needs_kill_escalation(tmp_path):
    """A worker stuck in native code ignores SIGTERM; abort() must
    escalate to SIGKILL and still reap — the hang-eviction teardown."""
    spec = sw.gpt_spec(config=CFG_KW, seed=0, engine=ENG_KW)
    h = sw.ProcReplica(spec, "wedge", str(tmp_path / "hb"),
                       policy=TransportPolicy(timeout=120.0, retries=0))
    assert h.wait_ready(timeout=300.0)
    pid = h.proc.pid
    h.ch.send({"cmd": "_wedge"})      # stops beating/reading, TERM-proof
    time.sleep(0.5)                   # let it enter the wedge
    h.abort()
    assert h.proc.poll() is not None
    assert h.proc.returncode == -signal.SIGKILL   # TERM was not enough
    with pytest.raises(ProcessLookupError):
        os.kill(pid, 0)


# ===================================================================
# tier-1 wiring of the kill -9 drill, under a wall-clock budget
# ===================================================================
def test_chaos_check_router_proc_drill():
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "chaos_check_proc", os.path.join(REPO, "tools",
                                         "chaos_check.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    buf = io.StringIO()
    t0 = time.monotonic()
    assert mod.run_router_proc(out=buf) == 0, buf.getvalue()
    elapsed = time.monotonic() - t0
    out = buf.getvalue()
    assert "kill -9'd 3x" in out
    assert "zero orphaned workers" in out
    # budget guard: the subprocess drill must fit tier-1's 870 s
    # timeout with plenty of room for the rest of the suite (the drill
    # itself re-checks PROC_BUDGET_S internally)
    assert elapsed < mod.PROC_BUDGET_S, (
        f"proc drill took {elapsed:.0f}s — too slow for tier-1")
