"""Resilience layer: chaos harness, nonfinite guard, checkpoint manager,
recovery supervisors.

Covers: spec grammar + plan determinism, the four fault families
end-to-end (tools/chaos_check.py wired in like tracelint --self),
crash-consistency of chaos-killed saves in BOTH orderings (latest() must
resolve to the previous good checkpoint), kill->respawn shm_loader
recovery, forced-NaN rollback with loss continuity after restore,
launch exponential backoff + crash-loop abort, and the precise
CheckpointError surface on partial/empty/torn checkpoint dirs.
"""
import os
import signal
import subprocess
import sys
import textwrap
import time
import warnings

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import CheckpointError, nn, optimizer as opt
from paddle_tpu.framework.checkpoint import load_state, save_state
from paddle_tpu.io import DataLoader, native
from paddle_tpu.jit.train_step import TrainStep
from paddle_tpu.resilience import chaos
from paddle_tpu.resilience.backoff import Backoff, CrashLoopDetector
from paddle_tpu.resilience.chaos import ChaosInterrupt, ChaosPlan
from paddle_tpu.resilience.guard import NonfiniteGuard
from paddle_tpu.resilience.manager import CheckpointManager

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _no_leaked_plan():
    yield
    chaos.uninstall()


def _make_step(guard=None, lr=0.1, momentum=None, seed=7):
    paddle.seed(seed)
    model = nn.Linear(4, 2)
    params = model.parameters()
    o = (opt.Momentum(learning_rate=lr, momentum=momentum,
                      parameters=params) if momentum
         else opt.SGD(learning_rate=lr, parameters=params))

    def loss_fn(m, x, y):
        return ((m(x) - y) ** 2).mean()

    return model, TrainStep(model, loss_fn, o, guard=guard)


def _batch(seed=0):
    rs = np.random.RandomState(seed)
    return (paddle.to_tensor(rs.randn(8, 4).astype("float32")),
            paddle.to_tensor(rs.randn(8, 2).astype("float32")))


# ===================================================================
# chaos: spec grammar + plan semantics
# ===================================================================
def test_spec_grammar():
    p = ChaosPlan("step.nonfinite@3;loader.worker_kill@2#1*2;"
                  "loader.batch_corrupt~0.25")
    e0, e1, e2 = p.entries
    assert (e0.site, e0.at, e0.tag, e0.repeat) == ("step.nonfinite", 3,
                                                   None, 1)
    assert (e1.site, e1.at, e1.tag, e1.repeat) == ("loader.worker_kill",
                                                   2, "1", 2)
    assert (e2.site, e2.prob) == ("loader.batch_corrupt", 0.25)
    # suffix order is free
    q = ChaosPlan("loader.worker_kill#1@2*2").entries[0]
    assert (q.at, q.tag, q.repeat) == (2, "1", 2)
    assert ChaosPlan("a.b*inf").entries[0].repeat == float("inf")


def test_fire_at_and_repeat():
    chaos.install(ChaosPlan("s.x@2*2"))
    assert [chaos.fire("s.x") for _ in range(5)] == [
        False, True, True, False, False]


def test_fire_tagged_counts_per_tag():
    chaos.install(ChaosPlan("s.x@2#b"))
    assert not chaos.fire("s.x", tag="a")
    assert not chaos.fire("s.x", tag="b")   # b's 1st hit
    assert not chaos.fire("s.x", tag="a")
    assert chaos.fire("s.x", tag="b")       # b's 2nd hit -> fires
    assert chaos.active().log == [("s.x", "b", 2)]


def test_probabilistic_entries_are_seeded():
    def draws(seed):
        p = ChaosPlan("s.x~0.5*inf", seed=seed)
        return [p.should_fire("s.x") for _ in range(32)]
    assert draws(3) == draws(3)             # deterministic per seed
    assert draws(3) != draws(4)             # and seed-sensitive


def test_disabled_is_fast_path_and_scoped_cleans_up():
    assert chaos.active() is None
    assert not chaos.fire("anything")
    with chaos.scoped("s.x@1") as plan:
        assert chaos.active() is plan
        with pytest.raises(ChaosInterrupt):
            chaos.crash("s.x")
    assert chaos.active() is None


def test_plan_from_env(monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_CHAOS", "s.y@1")
    monkeypatch.setenv("PADDLE_TPU_CHAOS_SEED", "9")
    plan = chaos.plan_from_env()
    assert plan is chaos.active()
    assert plan.seed == 9 and plan.entries[0].site == "s.y"


def test_chaos_interrupt_not_swallowed_by_except_exception():
    with pytest.raises(ChaosInterrupt):
        try:
            raise ChaosInterrupt("site")
        except Exception:                    # recovery code's net
            pytest.fail("ChaosInterrupt must bypass `except Exception`")


# ===================================================================
# backoff + crash loop
# ===================================================================
def test_backoff_schedule():
    b = Backoff(base=1.0, factor=2.0, max_delay=5.0)
    assert [b.delay(k) for k in range(4)] == [1.0, 2.0, 4.0, 5.0]
    assert Backoff(base=0).delay(10) == 0.0


def test_crash_loop_detector_window():
    t = [0.0]
    d = CrashLoopDetector(threshold=3, window=10.0, clock=lambda: t[0])
    assert not d.record_failure()
    t[0] = 1.0
    assert not d.record_failure()
    t[0] = 20.0                      # first two fall out of the window
    assert not d.record_failure()
    t[0] = 21.0
    assert not d.record_failure()
    t[0] = 22.0
    assert d.record_failure()        # 3 failures within 10s -> loop


# ===================================================================
# CheckpointError precision (satellite: no more bare FileNotFoundError)
# ===================================================================
def test_load_state_empty_dir_raises_checkpoint_error(tmp_path):
    d = tmp_path / "empty"
    d.mkdir()
    with pytest.raises(CheckpointError) as ei:
        load_state(str(d))
    assert ei.value.missing == "meta" and str(d) in str(ei.value)


def test_load_state_missing_arrays_raises_checkpoint_error(tmp_path):
    d = tmp_path / "partial"
    d.mkdir()
    (d / "meta.json").write_text("{}")
    with pytest.raises(CheckpointError) as ei:
        load_state(str(d))
    assert ei.value.missing == "arrays"


def test_load_state_names_orphaned_tmp(tmp_path):
    d = tmp_path / "torn"
    d.mkdir()
    (d / "meta.json.tmp").write_text("{}")
    with pytest.raises(CheckpointError, match="meta.json.tmp"):
        load_state(str(d))


def test_corrupt_meta_raises_checkpoint_error(tmp_path):
    model, ts = _make_step()
    ts(*_batch())
    path = str(tmp_path / "ck")
    save_state(path, model=model)
    chaos.corrupt_checkpoint(path, "corrupt_meta")
    with pytest.raises(CheckpointError) as ei:
        load_state(path, model=model)
    assert ei.value.missing == "meta"


# ===================================================================
# crash-consistency: chaos-killed save, BOTH orderings
# ===================================================================
@pytest.mark.parametrize("site", ["ckpt.crash_after_meta_stage",
                                  "ckpt.crash_after_arrays"])
def test_killed_save_falls_back_to_previous_good(tmp_path, site):
    model, ts = _make_step()
    ts(*_batch())
    mgr = CheckpointManager(str(tmp_path), max_to_keep=3)
    mgr.save(1, train_step=ts)
    with chaos.scoped(f"{site}@1"):
        with pytest.raises(ChaosInterrupt):
            mgr.save(2, train_step=ts)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", RuntimeWarning)
        assert mgr.latest() == mgr.path_for(1)
        meta = mgr.restore(train_step=ts)
    assert meta["step"] == 1
    # and the torn dir heals on the next save of the same step
    mgr.save(2, train_step=ts)
    assert mgr.latest() == mgr.path_for(2)
    assert not os.path.exists(
        os.path.join(mgr.path_for(2), "meta.json.tmp"))


def test_save_state_cleans_stale_tmp(tmp_path):
    model, ts = _make_step()
    ts(*_batch())
    path = str(tmp_path / "ck")
    save_state(path, model=model)
    stale = os.path.join(path, "meta.json.tmp")
    open(stale, "w").write("{stale}")
    save_state(path, model=model)        # must not publish the stale stage
    assert not os.path.exists(stale)
    load_state(path, model=model)


def test_manager_retention_gc(tmp_path):
    model, ts = _make_step()
    ts(*_batch())
    mgr = CheckpointManager(str(tmp_path), max_to_keep=2)
    for s in (1, 2, 3, 4):
        mgr.save(s, train_step=ts)
    assert mgr.all_steps() == [3, 4]
    assert mgr.latest() == mgr.path_for(4)


def test_manager_deep_fallback_past_truncated_arrays(tmp_path):
    """verify() passes a truncated-arrays checkpoint (meta is fine) but
    restore() must still walk back when the deep load fails."""
    model, ts = _make_step()
    ts(*_batch())
    mgr = CheckpointManager(str(tmp_path), max_to_keep=3)
    mgr.save(1, train_step=ts)
    mgr.save(2, train_step=ts)
    chaos.corrupt_checkpoint(mgr.path_for(2), "truncate_arrays")
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", RuntimeWarning)
        meta = mgr.restore(train_step=ts)
    assert meta["step"] == 1


def test_manager_restore_nothing_loadable_raises(tmp_path):
    mgr = CheckpointManager(str(tmp_path))
    with pytest.raises(CheckpointError, match="no loadable checkpoint"):
        mgr.restore()


# ===================================================================
# nonfinite-step guard
# ===================================================================
def test_guard_skips_bad_step_and_recovers():
    g = NonfiniteGuard(max_consecutive=10)
    model, ts = _make_step(guard=g)
    x, y = _batch()
    ts(x, y)
    w = np.asarray(model.weight.numpy()).copy()
    with chaos.scoped("step.nonfinite@1"):
        with warnings.catch_warnings():
            warnings.simplefilter("ignore", RuntimeWarning)
            loss = ts(x, y)
    assert not np.isfinite(float(loss.numpy()))      # loss reports truth
    assert np.allclose(np.asarray(model.weight.numpy()), w)  # no update
    assert g.total_skipped == 1 and g.consecutive == 1
    ts(x, y)                                          # finite step resets
    assert g.consecutive == 0
    assert not np.allclose(np.asarray(model.weight.numpy()), w)


def test_guard_without_manager_raises_after_threshold():
    g = NonfiniteGuard(max_consecutive=2)
    model, ts = _make_step(guard=g)
    x, y = _batch()
    with chaos.scoped("step.nonfinite@1*2"):
        with warnings.catch_warnings():
            warnings.simplefilter("ignore", RuntimeWarning)
            ts(x, y)
            with pytest.raises(FloatingPointError, match="consecutive"):
                ts(x, y)


def test_forced_nan_rollback_loss_continuity(tmp_path):
    """THE rollback pin: after N consecutive poisoned steps the guard
    restores the last checkpoint and the replayed steps produce exactly
    the losses of a run that never saw the poison (Momentum slots
    round-trip through the rollback too)."""
    batches = [_batch(seed=i) for i in range(6)]

    def drive(ts, upto, losses):
        while ts._step < upto:
            i = ts._step                     # pre-call index: a rollback
            val = float(ts(*batches[i]).numpy())   # rewinds _step inside
            if np.isfinite(val):             # skipped steps record no loss
                losses[i] = val

    # reference: clean run
    _, ref = _make_step(momentum=0.9, seed=11)
    ref_losses = {}
    drive(ref, 6, ref_losses)
    ref_w = np.asarray(ref.model.weight.numpy()).copy()

    # chaos run: checkpoint at 2, poison calls 3-4, rollback, replay
    mgr = CheckpointManager(str(tmp_path), max_to_keep=2)
    g = NonfiniteGuard(max_consecutive=2, manager=mgr, fold_rng=False)
    model, ts = _make_step(guard=g, momentum=0.9, seed=11)
    losses = {}
    drive(ts, 2, losses)
    mgr.save(2, train_step=ts)
    with chaos.scoped("step.nonfinite@3*2"):
        with warnings.catch_warnings():
            warnings.simplefilter("ignore", RuntimeWarning)
            drive(ts, 6, losses)
    assert g.rollbacks == 1 and g.total_skipped == 2
    assert np.allclose(np.asarray(model.weight.numpy()), ref_w,
                       atol=1e-6)
    for s in range(2, 6):
        assert np.isclose(losses[s], ref_losses[s], atol=1e-6), \
            (s, losses[s], ref_losses[s])


def test_guard_exact_mode_freezes_optimizer_slots():
    """mode="exact": a skipped step leaves even the adaptive moments
    byte-identical (mode="fused" lets them take one decay step)."""
    g = NonfiniteGuard(max_consecutive=10, mode="exact")
    model, ts = _make_step(guard=g, momentum=0.9)
    x, y = _batch()
    ts(x, y)
    ts.sync_optimizer_state()
    vel = [np.asarray(s["velocity"]).copy()
           for s in ts.optimizer._state]
    with chaos.scoped("step.nonfinite@1"):
        with warnings.catch_warnings():
            warnings.simplefilter("ignore", RuntimeWarning)
            ts(x, y)
    ts.sync_optimizer_state()
    for s, v in zip(ts.optimizer._state, vel):
        assert np.array_equal(np.asarray(s["velocity"]), v)
    assert g.total_skipped == 1


def test_guard_deferred_drain(tmp_path):
    """check_every=k: verdicts settle at the drain boundary, in step
    order, and a rollback discards the verdicts queued after it."""
    mgr = CheckpointManager(str(tmp_path), max_to_keep=2)
    g = NonfiniteGuard(max_consecutive=2, manager=mgr, check_every=4,
                       fold_rng=False)
    model, ts = _make_step(guard=g)
    x, y = _batch()
    ts(x, y)
    ts(x, y)
    mgr.save(2, train_step=ts)
    with chaos.scoped("step.nonfinite@1*2"):
        with warnings.catch_warnings():
            warnings.simplefilter("ignore", RuntimeWarning)
            ts(x, y)                         # bad, queued
            assert g.total_skipped == 0      # ...not yet detected
            ts(x, y)                         # bad, queued (4th verdict
            #   completes the window: drain fires inside this call)
    assert g.total_skipped == 2 and g.rollbacks == 1
    assert g._pending == []                  # post-rollback queue dropped
    assert ts._step == 2                     # rewound to the checkpoint


def test_guard_disabled_is_single_none_check():
    model, ts = _make_step(guard=None)
    assert ts._guard is None                 # env off -> no guard object
    x, y = _batch()
    ts(x, y)


def test_env_guard(monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_GUARD", "1")
    monkeypatch.setenv("PADDLE_TPU_GUARD_N", "5")
    model, ts = _make_step()
    assert isinstance(ts._guard, NonfiniteGuard)
    assert ts._guard.max_consecutive == 5


def test_guard_on_distributed_train_step():
    """The fleet engine's fused step takes the same guard: in-jit skip
    (replicated verdict, every shard gates identically), params frozen."""
    import paddle_tpu.distributed.fleet as fleet
    fleet.init(is_collective=True, strategy=fleet.DistributedStrategy())
    paddle.seed(0)
    model = nn.Linear(8, 4)
    o = opt.Adam(learning_rate=1e-3, parameters=model.parameters())
    g = NonfiniteGuard(max_consecutive=10)
    step = fleet.fleet.build_train_step(
        model, lambda m, x, y: ((m(x) - y) ** 2).mean(), o, guard=g)
    rs = np.random.RandomState(0)
    x = paddle.to_tensor(rs.randn(16, 8).astype("float32"))
    y = paddle.to_tensor(rs.randn(16, 4).astype("float32"))
    step(x, y)
    w = np.asarray(model.weight.numpy()).copy()
    with chaos.scoped("step.nonfinite@1"):
        with warnings.catch_warnings():
            warnings.simplefilter("ignore", RuntimeWarning)
            bad = step(x, y)
    assert not np.isfinite(float(bad.numpy()))
    assert np.allclose(np.asarray(model.weight.numpy()), w)
    assert g.total_skipped == 1
    assert np.isfinite(float(step(x, y).numpy()))


def test_compile_fail_once_recovers():
    model, ts = _make_step()
    x, y = _batch()
    with chaos.scoped("compile.fail_once@1"):
        with pytest.raises(ChaosInterrupt):
            ts(x, y)
        loss = ts(x, y)                      # retry rebuilds cleanly
    assert np.isfinite(float(loss.numpy()))


# ===================================================================
# preemption
# ===================================================================
def test_sigterm_sets_preempted_and_final_save(tmp_path):
    model, ts = _make_step()
    ts(*_batch())
    mgr = CheckpointManager(str(tmp_path), max_to_keep=2)
    mgr.install_preemption_handler()
    try:
        mgr.save(1, train_step=ts, async_save=True)
        os.kill(os.getpid(), signal.SIGTERM)
        assert mgr.preempted
        assert mgr.latest() == mgr.path_for(1)   # async save was flushed
        ts(*_batch())
        assert mgr.final_save() == mgr.path_for(ts._step)
    finally:
        mgr.uninstall_preemption_handler()


def test_mesh_change_detected_on_restore(tmp_path, monkeypatch):
    model, ts = _make_step()
    ts(*_batch())
    mgr = CheckpointManager(str(tmp_path))
    mgr.save(1, train_step=ts)
    import paddle_tpu.resilience.manager as mg
    monkeypatch.setattr(mg, "_mesh_info",
                        lambda: {"processes": 2, "devices": 16})
    with pytest.warns(RuntimeWarning, match="different mesh"):
        meta = mgr.restore(train_step=ts)
    assert meta["step"] == 1


# ===================================================================
# shm_loader recovery
# ===================================================================
needs_native = pytest.mark.skipif(not native.available(),
                                  reason="native ring unavailable")


class _SeqDataset:
    def __len__(self):
        return 16

    def __getitem__(self, i):
        return np.full((4,), i, dtype=np.float32)


def _collect(dl):
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        batches = [np.asarray(b.numpy()) for b in dl]
    return batches, [str(x.message) for x in w]


@needs_native
def test_loader_kill_respawn_preserves_batches():
    with chaos.scoped("loader.worker_kill@2#0"):
        dl = DataLoader(_SeqDataset(), batch_size=2, num_workers=2)
        batches, msgs = _collect(dl)
    assert [int(b[0, 0]) for b in batches] == list(range(0, 16, 2))
    assert any("respawning" in m for m in msgs)


@needs_native
def test_loader_kill_budget_exhausted_raises():
    with chaos.scoped("loader.worker_kill@1#0*inf"):
        dl = DataLoader(_SeqDataset(), batch_size=2, num_workers=1,
                        max_respawns=1)
        with pytest.raises(RuntimeError, match="respawn budget"):
            with warnings.catch_warnings():
                warnings.simplefilter("ignore", RuntimeWarning)
                list(dl)


@needs_native
def test_loader_corrupt_batch_skipped_not_fatal():
    with chaos.scoped("loader.batch_corrupt@1#1"):
        dl = DataLoader(_SeqDataset(), batch_size=2, num_workers=2)
        batches, msgs = _collect(dl)
    assert len(batches) == 7                  # one poisoned batch dropped
    assert any("batch skipped" in m for m in msgs)


@needs_native
@pytest.mark.slow
def test_loader_hang_timeout_respawn():
    with chaos.scoped("loader.worker_hang@1#0"):
        dl = DataLoader(_SeqDataset(), batch_size=2, num_workers=2,
                        timeout=2)
        batches, msgs = _collect(dl)
    assert len(batches) == 8
    assert any("wedged" in m for m in msgs)


# ===================================================================
# launch: backoff + crash loop + PT_RESTART_COUNT
# ===================================================================
def _write(tmp_path, name, body):
    p = tmp_path / name
    p.write_text(textwrap.dedent(body))
    return str(p)


def test_launch_backoff_and_restart_count(tmp_path):
    from paddle_tpu.distributed import launch
    script = _write(tmp_path, "flaky.py", """
        import os, sys
        d = os.path.dirname(os.path.abspath(__file__))
        marker = os.path.join(d, "attempts")
        n = int(open(marker).read()) if os.path.exists(marker) else 0
        open(marker, "w").write(str(n + 1))
        open(os.path.join(d, f"rc{n}"), "w").write(
            os.environ.get("PT_RESTART_COUNT", "?"))
        sys.exit(1 if n < 2 else 0)
    """)
    t0 = time.monotonic()
    code = launch.run(["--nproc_per_node", "1", "--max_restarts", "3",
                       "--restart_backoff", "0.2",
                       "--crash_loop_threshold", "0", script])
    assert code == 0
    assert (tmp_path / "attempts").read_text() == "3"
    assert [(tmp_path / f"rc{i}").read_text() for i in range(3)] == \
        ["0", "1", "2"]
    assert time.monotonic() - t0 >= 0.6       # 0.2s + 0.4s backoffs


def test_launch_crash_loop_aborts_early(tmp_path):
    from paddle_tpu.distributed import launch
    script = _write(tmp_path, "dead.py", "import sys; sys.exit(7)\n")
    code = launch.run(["--nproc_per_node", "1", "--max_restarts", "99",
                       "--restart_backoff", "0.05",
                       "--crash_loop_threshold", "3",
                       "--crash_loop_window", "60", script])
    assert code == 7                          # aborted, not 99 restarts


# ===================================================================
# hapi: ResilienceCallback auto-resume
# ===================================================================
def _fit_model(tmp_path, epochs, callbacks=None):
    import paddle_tpu.hapi as hapi
    paddle.seed(123)
    net = nn.Linear(4, 1)
    m = hapi.Model(net)
    m.prepare(optimizer=opt.SGD(learning_rate=0.05,
                                parameters=net.parameters()),
              loss=nn.MSELoss())
    rs = np.random.RandomState(42)
    X = rs.randn(32, 4).astype("float32")
    Y = (X @ rs.randn(4, 1)).astype("float32")
    ds = [(X[i], Y[i]) for i in range(32)]
    m.fit(ds, batch_size=8, epochs=epochs, verbose=0, shuffle=False,
          callbacks=callbacks)
    return m


def test_resilience_callback_resume_matches_uninterrupted(tmp_path,
                                                          capsys):
    from paddle_tpu.hapi import ResilienceCallback
    ref = _fit_model(tmp_path, epochs=3)
    ref_w = np.asarray(ref.network.weight.numpy()).copy()

    ck = str(tmp_path / "ck")
    _fit_model(tmp_path, epochs=2, callbacks=[
        ResilienceCallback(checkpoint_dir=ck, save_freq=1,
                           async_save=False)])
    resumed = _fit_model(tmp_path, epochs=1, callbacks=[
        ResilienceCallback(checkpoint_dir=ck, save_freq=1,
                           async_save=False)])
    assert "resumed from" in capsys.readouterr().out
    assert np.allclose(np.asarray(resumed.network.weight.numpy()),
                       ref_w, atol=1e-6)


# ===================================================================
# the seeded chaos plan, end-to-end (tier-1 wiring of chaos_check)
# ===================================================================
def _load_chaos_check():
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "chaos_check", os.path.join(REPO, "tools", "chaos_check.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_chaos_check_inprocess():
    """All four fault families under one seeded plan; the recovered run
    must match the uninterrupted reference exactly."""
    import io
    buf = io.StringIO()
    assert _load_chaos_check().run(out=buf) == 0, buf.getvalue()
    assert "all four fault families recovered" in buf.getvalue()


@pytest.mark.slow
def test_chaos_check_subprocess():
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "chaos_check.py")],
        capture_output=True, text=True,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    assert r.returncode == 0, r.stdout + r.stderr


def test_chaos_check_mesh_change_inprocess():
    """The elastic restart drill: a run killed on a 4-device mesh resumes
    on a 2-device mesh via device-side resharding (no replicated host
    bounce), its post-restore loss trajectory matches the uninterrupted
    reference, and an injected collective.timeout is retried by the
    collective policy without supervisor intervention."""
    import io
    from paddle_tpu.distributed import mesh as mesh_mod
    prev = dict(mesh_mod._state)
    buf = io.StringIO()
    try:
        rc = _load_chaos_check().run_mesh_change(out=buf)
    finally:
        mesh_mod._state.update(prev)
    assert rc == 0, buf.getvalue()
    assert "resumed on dp=2 via device-side resharding" in buf.getvalue()


def test_chaos_check_cold_start_inprocess():
    """The cold-start drill: a run trained with a persistent compile
    cache is killed; the restart (a REAL subprocess) performs zero
    compilations — every jit entry loads its serialized executable —
    with bit-exact loss/weight continuity; a deterministically corrupted
    cache entry is quarantined and transparently recompiled."""
    import io
    from paddle_tpu.jit import compile_cache as cc
    buf = io.StringIO()
    try:
        rc = _load_chaos_check().run_cold_start(out=buf)
    finally:
        cc.reset()
    assert rc == 0, buf.getvalue()
    assert "zero recompiles" in buf.getvalue()
    assert "quarantined" in buf.getvalue()


@pytest.mark.slow
def test_chaos_check_mesh_change_subprocess():
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "chaos_check.py"),
         "--mesh-change"],
        capture_output=True, text=True,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    assert r.returncode == 0, r.stdout + r.stderr
