"""paddle.sparse tests (COO/CSR over jax BCOO)."""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import sparse as S


def _coo_example():
    # [[1, 0, 2], [0, 3, 0]]
    idx = np.array([[0, 0, 1], [0, 2, 1]])
    vals = np.array([1.0, 2.0, 3.0], np.float32)
    return S.sparse_coo_tensor(idx, vals, [2, 3])


def test_coo_roundtrip():
    t = _coo_example()
    assert t.shape == [2, 3] and t.nnz() == 3
    dense = t.to_dense().numpy()
    np.testing.assert_allclose(dense, [[1, 0, 2], [0, 3, 0]])
    np.testing.assert_allclose(t.values().numpy(), [1, 2, 3])
    assert t.indices().numpy().shape == (2, 3)


def test_csr_construction():
    # same matrix in CSR
    t = S.sparse_csr_tensor(crows=[0, 2, 3], cols=[0, 2, 1],
                            values=np.array([1.0, 2.0, 3.0], np.float32),
                            shape=[2, 3])
    np.testing.assert_allclose(t.to_dense().numpy(),
                               [[1, 0, 2], [0, 3, 0]])


def test_from_dense_and_elementwise():
    d = np.array([[0, 1], [2, 0]], np.float32)
    t = S.SparseCooTensor.from_dense(pt.to_tensor(d))
    assert t.nnz() == 2
    s2 = S.add(t, t)
    np.testing.assert_allclose(s2.to_dense().numpy(), 2 * d)
    s3 = S.subtract(s2, t)
    np.testing.assert_allclose(s3.to_dense().numpy(), d)
    s4 = S.multiply(t, 3.0)
    np.testing.assert_allclose(s4.to_dense().numpy(), 3 * d)


def test_multiply_dense_mask_semantics():
    t = _coo_example()
    y = np.full((2, 3), 2.0, np.float32)
    out = S.multiply(t, y)
    np.testing.assert_allclose(out.to_dense().numpy(),
                               [[2, 0, 4], [0, 6, 0]])


def test_spmm_and_dense_matmul():
    t = _coo_example()
    w = np.random.RandomState(0).randn(3, 4).astype(np.float32)
    out = S.matmul(t, pt.to_tensor(w))
    np.testing.assert_allclose(out.numpy(),
                               t.to_dense().numpy() @ w, rtol=1e-5)


def test_masked_matmul_sddmm():
    rng = np.random.RandomState(1)
    x = rng.randn(4, 8).astype(np.float32)
    y = rng.randn(8, 4).astype(np.float32)
    mask = S.SparseCooTensor.from_dense(
        pt.to_tensor(np.eye(4, dtype=np.float32)))
    out = S.masked_matmul(pt.to_tensor(x), pt.to_tensor(y), mask)
    full = x @ y
    np.testing.assert_allclose(out.to_dense().numpy(),
                               np.eye(4) * full, rtol=1e-4)


def test_relu_transpose_astype():
    idx = np.array([[0, 1], [1, 0]])
    t = S.sparse_coo_tensor(idx, np.array([-1.0, 2.0], np.float32), [2, 2])
    r = S.relu(t)
    np.testing.assert_allclose(r.to_dense().numpy(), [[0, 0], [2, 0]])
    tt = S.transpose(t, [1, 0])
    np.testing.assert_allclose(tt.to_dense().numpy(),
                               t.to_dense().numpy().T)
    t16 = t.astype("float16")
    assert str(t16.dtype) == "float16"


def test_coalesce_merges_duplicates():
    idx = np.array([[0, 0], [0, 0]])  # same position twice
    t = S.sparse_coo_tensor(idx, np.array([1.0, 2.0], np.float32), [1, 1])
    c = t.coalesce()
    np.testing.assert_allclose(c.to_dense().numpy(), [[3.0]])


def test_sparse_times_sparse_and_broadcast():
    t = _coo_example()          # [[1,0,2],[0,3,0]]
    out = S.multiply(t, t)
    np.testing.assert_allclose(out.to_dense().numpy(),
                               [[1, 0, 4], [0, 9, 0]])
    # row-broadcast dense operand
    row = np.array([[2.0, 2.0, 2.0]], np.float32)   # [1, 3]
    out = S.multiply(t, row)
    np.testing.assert_allclose(out.to_dense().numpy(),
                               [[2, 0, 4], [0, 6, 0]])
    # 0-d numpy scalar hits the scalar path
    out = S.multiply(t, np.float32(3.0))
    np.testing.assert_allclose(out.to_dense().numpy(),
                               [[3, 0, 6], [0, 9, 0]])
    out = S.divide(t, t)
    np.testing.assert_allclose(out.to_dense().numpy(),
                               [[1, 0, 1], [0, 1, 0]])


def test_empty_sparse_requires_shape():
    with pytest.raises(ValueError, match="shape"):
        S.sparse_coo_tensor(np.zeros((2, 0)), np.zeros((0,)))
    t = S.sparse_coo_tensor(np.zeros((2, 0)), np.zeros((0,), np.float32),
                            shape=[2, 2])
    np.testing.assert_allclose(t.to_dense().numpy(), np.zeros((2, 2)))


def test_masked_matmul_batched():
    rng = np.random.RandomState(2)
    x = rng.randn(2, 3, 4).astype(np.float32)
    y = rng.randn(2, 4, 3).astype(np.float32)
    eye = np.stack([np.eye(3, dtype=np.float32)] * 2)
    mask = S.SparseCooTensor.from_dense(pt.to_tensor(eye))
    out = S.masked_matmul(pt.to_tensor(x), pt.to_tensor(y), mask)
    full = np.einsum("bmk,bkn->bmn", x, y)
    np.testing.assert_allclose(out.to_dense().numpy(), eye * full,
                               rtol=1e-4)


def test_int_sparse_scalar_keeps_dtype_and_div_zero():
    idx = np.array([[0], [0]])
    t = S.sparse_coo_tensor(idx, np.array([4], np.int32), [1, 1])
    out = S.multiply(t, 2)
    assert "int" in str(out.dtype)
    f = S.sparse_coo_tensor(idx, np.array([4.0], np.float32), [1, 1])
    d = S.divide(f, 0)
    assert np.isinf(d.values().numpy()).all()  # inf, not ZeroDivisionError
