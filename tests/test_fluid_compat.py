"""paddle.base / paddle.fluid legacy-namespace compatibility."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.framework import static_graph as SG


def test_dygraph_guard_and_to_variable():
    with paddle.fluid.dygraph.guard():
        x = paddle.fluid.dygraph.to_variable(np.ones((2, 3), np.float32))
        y = paddle.fluid.layers.relu(x - 2.0)
        assert float(y.sum()) == 0.0
    assert paddle.fluid.CUDAPlace(0) is not None
    assert not paddle.fluid.is_compiled_with_cuda()


def test_fluid_static_program():
    paddle.enable_static()
    SG.reset()
    try:
        main = paddle.fluid.Program()
        with paddle.fluid.program_guard(main):
            d = paddle.fluid.layers.data("x", [None, 4], "float32")
            h = paddle.fluid.layers.fc(d, 2, act="relu")
        exe = paddle.fluid.Executor(paddle.fluid.CPUPlace())
        (hv,) = exe.run(main, feed={"x": np.ones((3, 4), np.float32)},
                        fetch_list=[h])
        assert hv.shape == (3, 2) and (hv >= 0).all()
    finally:
        SG.reset()
        paddle.disable_static()


def test_lod_tensor_guidance():
    with pytest.raises(NotImplementedError, match="sequence_mask"):
        paddle.fluid.create_lod_tensor(None, None, None)
