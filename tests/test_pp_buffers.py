"""Buffer-mutating blocks under pp (VERDICT r3 item 7; reference: fleet
pp trains BN-bearing convnets).

Train-mode BatchNorm running stats now update inside the pipelined
schedule: the per-device buffer stack rides the schedule scan as a carry
(microbatches commit in order — serial semantics), the updated stacks
come back as explicit outputs, and the engine folds them onto the model's
buffers.  Pinned: BN stats + loss match a serial per-microbatch run for
both schedules, across multiple steps."""
import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.nn.functional as F
from paddle_tpu.distributed import fleet
from paddle_tpu.distributed import mesh as mesh_mod


@pytest.fixture
def restore_mesh():
    prev = dict(mesh_mod._state)
    yield
    mesh_mod._state.update(prev)


class BNBlock(pt.nn.Layer):
    def __init__(self, width):
        super().__init__()
        self.fc = pt.nn.Linear(width, width)
        self.bn = pt.nn.BatchNorm1D(width)

    def forward(self, x):
        return F.relu(self.bn(self.fc(x)))


class BNNet(pt.nn.Layer):
    """ResNet-ish stack: homogeneous Linear+BN blocks + a head."""

    def __init__(self, width=16, n_blocks=4, n_classes=4):
        super().__init__()
        self.blocks = pt.nn.LayerList(
            [BNBlock(width) for _ in range(n_blocks)])
        self.head = pt.nn.Linear(width, n_classes)

    def forward(self, x):
        for b in self.blocks:
            x = b(x)
        return self.head(x)

    def pipeline_decompose(self):
        return {"blocks": list(self.blocks), "pre": lambda x: x,
                "post": self.head}


def loss_fn(model, x, y):
    return F.cross_entropy(model(x), y, reduction="mean")


def _bn_stats(model):
    return {n: np.asarray(b._array)
            for n, b in model.named_buffers() if "_mean" in n
            or "_variance" in n}


@pytest.mark.parametrize("sched,vpp,M", [
    ("1F1B", 1, 2),
    ("F-then-B", 1, 2),
    ("1F1B", 2, 4),
    pytest.param("F-then-B", 2, 4, marks=pytest.mark.xfail(
        strict=False,
        reason="pre-existing at seed: interleaved-buffer numeric drift "
               "under jax 0.4.37's old-shard_map compat path "
               "(framework/compat.py); unblocks with the ROADMAP "
               "item-3c migration off the compat shims")),
])
def test_pp_bn_running_stats_match_serial(restore_mesh, sched, vpp, M):
    B, width = 8, 16
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 1, "mp_degree": 1,
                               "pp_degree": 2, "accumulate_steps": M,
                               "pp_schedule": sched,
                               "virtual_pp_degree": vpp}
    fleet.init(is_collective=True, strategy=strategy)
    pt.seed(0)
    m_pp = BNNet(width)
    pt.seed(0)
    m_ref = BNNet(width)
    m_ref.set_state_dict(m_pp.state_dict())

    o_pp = pt.optimizer.SGD(learning_rate=0.1,
                            parameters=m_pp.parameters())
    o_ref = pt.optimizer.SGD(learning_rate=0.1,
                             parameters=m_ref.parameters())
    step = fleet.build_train_step(m_pp, loss_fn, o_pp)

    pt.seed(7)
    x = pt.randn([B, width])
    y = pt.randint(0, 4, [B])

    for _ in range(3):   # multi-step: stats must flow step to step
        pp_loss = float(step(x, y))

        # serial reference: per-microbatch forward in order (BN batch
        # stats are per-microbatch under pp — the reference's semantics)
        outs = []
        for m in range(M):
            xs = x[m * (B // M):(m + 1) * (B // M)]
            outs.append(m_ref(xs))
        import paddle_tpu.tensor_api as T
        ref_loss = F.cross_entropy(T.concat(outs, axis=0), y,
                                   reduction="mean")
        ref_loss.backward()
        o_ref.step()
        o_ref.clear_grad()
        assert abs(pp_loss - float(ref_loss)) < 3e-5, (pp_loss,
                                                       float(ref_loss))

    step.sync_model()
    s_pp, s_ref = _bn_stats(m_pp), _bn_stats(m_ref)
    assert s_pp.keys() == s_ref.keys() and len(s_pp) == 8
    # single-step stats are exact to ~3e-8; over 3 TRAINING steps fp32
    # accumulation-order drift in the param updates compounds into the
    # stats — a real ordering bug shows up at O(1e-2), so 1e-3/3e-5
    # still discriminates
    for n in s_pp:
        np.testing.assert_allclose(s_pp[n], s_ref[n], rtol=1e-3,
                                   atol=3e-5, err_msg=n)
    # trained weights stay in lockstep too
    for k, v in m_ref.state_dict().items():
        np.testing.assert_allclose(
            np.asarray(dict(m_pp.state_dict())[k]._array),
            np.asarray(v._array), rtol=3e-4, atol=3e-5, err_msg=k)


# round 4: the F-then-B interleaved scan threads buffers too (covered by
# the parametrized parity test above) — the read-only guard is gone from
# every schedule.
