"""Auto-parallel API tests (reference: paddle.distributed ProcessMesh /
shard_tensor / reshard / placements) on the 8-virtual-device CPU mesh."""
import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.distributed as dist


def test_process_mesh_build():
    mesh = dist.ProcessMesh([[0, 1, 2, 3], [4, 5, 6, 7]],
                            dim_names=["x", "y"])
    assert mesh.shape == [2, 4]
    assert mesh.dim_names == ["x", "y"]
    assert mesh.process_ids == list(range(8))


def test_shard_tensor_layout_and_values():
    mesh = dist.ProcessMesh([[0, 1, 2, 3], [4, 5, 6, 7]],
                            dim_names=["x", "y"])
    x = np.arange(8 * 4, dtype=np.float32).reshape(8, 4)
    t = dist.shard_tensor(x, mesh, [dist.Shard(0), dist.Replicate()])
    # values preserved, sharding applied on dim 0 over mesh dim "x"
    np.testing.assert_array_equal(t.numpy(), x)
    assert t.pspec[0] == "x" and t.pspec[1] is None
    shard_shape = t._array.sharding.shard_shape(t._array.shape)
    assert shard_shape == (4, 4)  # 8 rows / x-dim degree 2


def test_shard_tensor_both_dims():
    mesh = dist.ProcessMesh([[0, 1, 2, 3], [4, 5, 6, 7]],
                            dim_names=["x", "y"])
    x = np.arange(4 * 8, dtype=np.float32).reshape(4, 8)
    t = dist.shard_tensor(x, mesh, [dist.Shard(0), dist.Shard(1)])
    shard_shape = t._array.sharding.shard_shape(t._array.shape)
    assert shard_shape == (2, 2)  # 4/2 x 8/4


def test_reshard_changes_layout():
    mesh = dist.ProcessMesh([0, 1, 2, 3], dim_names=["x"])
    x = np.arange(16, dtype=np.float32).reshape(8, 2)
    t = dist.shard_tensor(x, mesh, [dist.Shard(0)])
    r = dist.reshard(t, mesh, [dist.Replicate()])
    np.testing.assert_array_equal(r.numpy(), x)
    assert r._array.sharding.shard_shape(r._array.shape) == (8, 2)
    pl = dist.auto_parallel.get_placements(t)
    assert pl == [dist.Shard(0)]


def test_dtensor_from_fn_and_compute():
    """Sharded tensors flow through ordinary ops; GSPMD handles layout."""
    mesh = dist.ProcessMesh([0, 1, 2, 3], dim_names=["mp_"])
    w = dist.dtensor_from_fn(pt.ones, mesh, [dist.Shard(1)], [4, 8])
    x = pt.randn([2, 4])
    y = x @ w   # [2, 8] — XLA inserts what the layout needs
    assert tuple(y.shape) == (2, 8)
    np.testing.assert_allclose(y.numpy(), x.numpy() @ np.ones((4, 8)),
                               rtol=1e-5)


def test_placement_validation():
    mesh = dist.ProcessMesh([0, 1], dim_names=["x"])
    with pytest.raises(ValueError):
        dist.shard_tensor(np.zeros((4,)), mesh,
                          [dist.Shard(0), dist.Shard(1)])  # too many
    with pytest.raises(ValueError):
        dist.shard_tensor(np.zeros((4,)), mesh, [dist.Shard(3)])
    with pytest.raises(ValueError):
        dist.ProcessMesh([[0, 1]], dim_names=["x"])  # ndim mismatch


def test_shard_tensor_in_training():
    """Auto-parallel placement composes with the fused train step: dp-style
    batch sharding + replicated params."""
    import paddle_tpu.nn.functional as F
    mesh = dist.ProcessMesh(list(range(8)), dim_names=["dp_"])
    pt.seed(0)
    m = pt.nn.Linear(8, 4)
    opt = pt.optimizer.SGD(learning_rate=0.1, parameters=m.parameters())
    step = pt.jit.train_step(m, lambda mm, a, b: F.mse_loss(mm(a), b), opt)
    x = dist.shard_tensor(np.random.RandomState(0).randn(16, 8)
                          .astype(np.float32), mesh, [dist.Shard(0)])
    y = dist.shard_tensor(np.random.RandomState(1).randn(16, 4)
                          .astype(np.float32), mesh, [dist.Shard(0)])
    l0 = float(step(x, y))
    l1 = float(step(x, y))
    assert l1 < l0


def test_review_regressions():
    """Multi-output jacobian keeps all outputs; placements/mesh hashable;
    negative ids rejected; unsupported kwargs raise."""
    from paddle_tpu.autograd import jacobian
    x = pt.to_tensor(np.array([1.0, 2.0], np.float32))
    j = jacobian(lambda t: (t ** 2, t ** 3), x)
    assert isinstance(j, tuple) and len(j) == 2
    np.testing.assert_allclose(j[1].numpy(),
                               np.diag(3 * np.array([1.0, 4.0])), rtol=1e-5)
    with pytest.raises(NotImplementedError):
        jacobian(lambda t: t, x, batch_axis=0)

    assert dist.Partial() == dist.Partial()
    m1 = dist.ProcessMesh([0, 1], dim_names=["x"])
    m2 = dist.ProcessMesh([0, 1], dim_names=["x"])
    assert len({m1, m2}) == 1
    with pytest.raises(ValueError, match="process ids"):
        dist.ProcessMesh([0, -1], dim_names=["x"])
