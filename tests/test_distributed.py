"""Distributed tests on the 8-virtual-CPU mesh (SURVEY §4):
tp == dense, zero stages == unsharded, ring == full, pipeline == serial."""
import numpy as np
import pytest
import jax
import jax.numpy as jnp

import paddle_tpu as pt
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu.distributed import fleet, mesh as mesh_mod



@pytest.fixture
def mesh_2x2():
    prev = dict(mesh_mod._state)
    mesh_mod.build_mesh(dp=2, pp=1, mp=2)
    yield mesh_mod.get_mesh()
    mesh_mod._state.update(prev)


@pytest.fixture
def mesh_sp4():
    prev = dict(mesh_mod._state)
    mesh_mod.build_mesh(dp=1, pp=1, mp=4)
    yield mesh_mod.get_mesh()
    mesh_mod._state.update(prev)


def test_mesh_build():
    prev = dict(mesh_mod._state)
    m = mesh_mod.build_mesh(dp=2, pp=2, mp=2)
    assert m.shape == {"dp": 2, "pp": 2, "mp": 2}
    assert mesh_mod.degree("mp") == 2
    mesh_mod._state.update(prev)


def test_column_row_parallel_match_dense(mesh_2x2):
    from paddle_tpu.distributed import (ColumnParallelLinear,
                                        RowParallelLinear)
    pt.seed(1)
    col = ColumnParallelLinear(8, 16)
    row = RowParallelLinear(16, 8)
    dense1 = nn.Linear(8, 16)
    dense2 = nn.Linear(16, 8)
    dense1.weight.set_value(col.weight); dense1.bias.set_value(col.bias)
    dense2.weight.set_value(row.weight); dense2.bias.set_value(row.bias)
    x = pt.randn([4, 8])
    np.testing.assert_allclose(row(col(x)).numpy(),
                               dense2(dense1(x)).numpy(), rtol=1e-5)
    assert col.weight.pspec is not None


def test_ring_attention_matches_full(mesh_sp4):
    from paddle_tpu.distributed.ring_attention import ring_attention
    from paddle_tpu.ops.dispatch import call_raw
    np.random.seed(0)
    B, L, H, D = 2, 32, 4, 16
    q, k, v = (jnp.asarray(np.random.randn(B, L, H, D), jnp.float32)
               for _ in range(3))
    for causal in (True, False):
        ring = ring_attention(q, k, v, causal=causal)
        full = call_raw("sdpa", q, k, v, None, is_causal=causal)
        np.testing.assert_allclose(np.asarray(ring), np.asarray(full),
                                   atol=2e-5)


def test_pipeline_matches_serial():
    from paddle_tpu.distributed.pipeline import pipeline_apply
    prev = dict(mesh_mod._state)
    mesh = mesh_mod.build_mesh(dp=1, pp=4, mp=1)
    np.random.seed(0)
    D, n_stages, lps = 8, 4, 2
    w = jnp.asarray(np.random.randn(n_stages, lps, D, D) * 0.1, jnp.float32)
    b = jnp.asarray(np.random.randn(n_stages, lps, D) * 0.1, jnp.float32)

    def stage_fn(sp, x):
        def blk(h, lp):
            return jnp.tanh(h @ lp["w"] + lp["b"]), None
        y, _ = jax.lax.scan(blk, x, sp)
        return y

    M, mb = 4, 4
    x = jnp.asarray(np.random.randn(M, mb, D), jnp.float32)
    out = pipeline_apply(stage_fn, {"w": w, "b": b}, x, mesh, n_stages, M)
    ref = x
    for s in range(n_stages):
        for l in range(lps):
            ref = jnp.tanh(ref @ w[s, l] + b[s, l])
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)
    mesh_mod._state.update(prev)


def _tiny_model_and_data(seed=5):
    pt.seed(seed)
    m = nn.Sequential(nn.Linear(8, 16), nn.Tanh(), nn.Linear(16, 8))
    x = pt.randn([8, 8]); y = pt.randn([8, 8])
    return m, x, y


def _loss_fn(model, xi, yi):
    return F.mse_loss(model(xi), yi)


@pytest.mark.parametrize("stage", [0, 1, 2, 3])
def test_zero_stages_match_unsharded(stage):
    prev = dict(mesh_mod._state)
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 4, "mp_degree": 1, "pp_degree": 1,
                               "sharding_degree": 4, "sharding_stage": stage}
    fleet.init(is_collective=True, strategy=strategy)

    m1, x, y = _tiny_model_and_data()
    m2, _, _ = _tiny_model_and_data()
    m2.set_state_dict(m1.state_dict())

    o1 = pt.optimizer.Adam(learning_rate=0.05, parameters=m1.parameters())
    step = fleet.build_train_step(m1, _loss_fn, o1)
    o2 = pt.optimizer.Adam(learning_rate=0.05, parameters=m2.parameters())

    for _ in range(3):
        dist_loss = step(x, y)
        ref_loss = _loss_fn(m2, x, y)
        ref_loss.backward()
        o2.step(); o2.clear_grad()
        np.testing.assert_allclose(float(dist_loss), float(ref_loss),
                                   rtol=1e-4)
    for (n1, p1), (_, p2) in zip(m1.named_parameters(),
                                 m2.named_parameters()):
        np.testing.assert_allclose(p1.numpy(), p2.numpy(), rtol=1e-3,
                                   atol=1e-5)
    mesh_mod._state.update(prev)


def test_fleet_gpt_tp_matches_dense():
    """GPT forward with mp=2 sharded weights == same weights dense."""
    from paddle_tpu.text import GPTConfig, GPTForCausalLM
    prev = dict(mesh_mod._state)
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 2, "pp_degree": 1}
    fleet.init(is_collective=True, strategy=strategy)

    pt.seed(11)
    cfg_tp = GPTConfig(vocab_size=64, hidden_size=32, num_layers=2,
                       num_heads=4, max_position_embeddings=32,
                       hidden_dropout=0.0, attention_dropout=0.0,
                       tensor_parallel=True)
    m_tp = GPTForCausalLM(cfg_tp)
    cfg_d = GPTConfig(vocab_size=64, hidden_size=32, num_layers=2,
                      num_heads=4, max_position_embeddings=32,
                      hidden_dropout=0.0, attention_dropout=0.0,
                      tensor_parallel=False)
    m_d = GPTForCausalLM(cfg_d)
    m_d.set_state_dict(m_tp.state_dict())
    m_tp.eval(); m_d.eval()
    ids = pt.randint(0, 64, [2, 8])
    np.testing.assert_allclose(m_tp(ids).numpy(), m_d(ids).numpy(),
                               rtol=1e-4, atol=1e-5)
    mesh_mod._state.update(prev)


def _tiny_gpt(tp, seed=13, layers=4, recompute=False):
    from paddle_tpu.text import GPTConfig, GPTForCausalLM
    pt.seed(seed)
    cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=layers,
                    num_heads=4, max_position_embeddings=32,
                    hidden_dropout=0.0, attention_dropout=0.0,
                    use_recompute=recompute, tensor_parallel=tp)
    return GPTForCausalLM(cfg)


@pytest.mark.parametrize("hybrid", [
    {"dp_degree": 2, "mp_degree": 1, "pp_degree": 2},
    {"dp_degree": 2, "mp_degree": 2, "pp_degree": 2},
    {"dp_degree": 1, "mp_degree": 2, "pp_degree": 2,
     "sharding_degree": 1, "sharding_stage": 0, "accumulate_steps": 4},
    # interleaved (virtual) pipeline: M > P exercises the inter-chunk FIFO
    {"dp_degree": 2, "mp_degree": 1, "pp_degree": 2,
     "accumulate_steps": 4, "virtual_pp_degree": 2},
    # M == P: zero-delay wrap-around path
    {"dp_degree": 2, "mp_degree": 1, "pp_degree": 2,
     "accumulate_steps": 2, "virtual_pp_degree": 2},
])
@pytest.mark.needs_partial_manual
def test_fleet_gpt_pipeline_matches_serial(hybrid):
    """pp>1 fleet step == serial eager training (loss + params)."""
    from paddle_tpu.text import gpt_loss_fn
    prev = dict(mesh_mod._state)
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = dict(hybrid)
    fleet.init(is_collective=True, strategy=strategy)

    m_pp = _tiny_gpt(tp=hybrid.get("mp_degree", 1) > 1)
    m_ref = _tiny_gpt(tp=False, seed=99)
    m_ref.set_state_dict(m_pp.state_dict())

    o_pp = pt.optimizer.Adam(learning_rate=0.02,
                             parameters=m_pp.parameters())
    step = fleet.build_train_step(m_pp, gpt_loss_fn, o_pp)
    o_ref = pt.optimizer.Adam(learning_rate=0.02,
                              parameters=m_ref.parameters())

    pt.seed(7)
    ids = pt.randint(0, 64, [8, 16])
    labels = pt.randint(0, 64, [8, 16])
    for _ in range(3):
        pp_loss = step(ids, labels)
        ref_loss = gpt_loss_fn(m_ref, ids, labels)
        ref_loss.backward()
        o_ref.step(); o_ref.clear_grad()
        np.testing.assert_allclose(float(pp_loss), float(ref_loss),
                                   rtol=2e-4)
    step.sync_model()
    ref_params = dict(m_ref.named_parameters())
    for n, p in m_pp.named_parameters():
        np.testing.assert_allclose(p.numpy(), ref_params[n].numpy(),
                                   rtol=1e-3, atol=3e-4)
    mesh_mod._state.update(prev)


@pytest.mark.needs_partial_manual
def test_fleet_gpt_pipeline_with_remat_and_zero():
    """pp + recompute + ZeRO-1 still matches serial losses."""
    from paddle_tpu.text import gpt_loss_fn
    prev = dict(mesh_mod._state)
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 1,
                               "pp_degree": 2, "sharding_degree": 2,
                               "sharding_stage": 1}
    fleet.init(is_collective=True, strategy=strategy)

    m_pp = _tiny_gpt(tp=False, recompute=True)
    m_ref = _tiny_gpt(tp=False, seed=99)
    m_ref.set_state_dict(m_pp.state_dict())
    o_pp = pt.optimizer.Adam(learning_rate=0.02,
                             parameters=m_pp.parameters())
    step = fleet.build_train_step(m_pp, gpt_loss_fn, o_pp)
    o_ref = pt.optimizer.Adam(learning_rate=0.02,
                              parameters=m_ref.parameters())
    pt.seed(3)
    ids = pt.randint(0, 64, [4, 16])
    labels = pt.randint(0, 64, [4, 16])
    for _ in range(2):
        pp_loss = step(ids, labels)
        ref_loss = gpt_loss_fn(m_ref, ids, labels)
        ref_loss.backward()
        o_ref.step(); o_ref.clear_grad()
        np.testing.assert_allclose(float(pp_loss), float(ref_loss),
                                   rtol=2e-4)
    # state_dict auto-syncs the stacked pp stage params (no explicit
    # sync_model call) — trained block weights must match the reference
    sd = m_pp.state_dict()
    ref = dict(m_ref.named_parameters())
    k = "gpt.h.1.mlp.fc_in.weight"
    np.testing.assert_allclose(sd[k].numpy(), ref[k].numpy(),
                               rtol=1e-3, atol=3e-4)
    mesh_mod._state.update(prev)


def test_collective_api_eager():
    from paddle_tpu import distributed as dist
    t = pt.ones([4])
    dist.all_reduce(t)  # single-process: identity
    np.testing.assert_allclose(t.numpy(), np.ones(4))
    assert dist.get_world_size() >= 1
    assert dist.get_rank() == 0


def test_shard_activation_noop_without_mesh():
    from paddle_tpu.distributed import shard_activation
    prev = dict(mesh_mod._state)
    mesh_mod._state["mesh"] = None
    mesh_mod._state["degrees"] = None
    x = pt.ones([4, 4])
    assert shard_activation(x, (None, None)) is x
    mesh_mod._state.update(prev)


def test_bubble_fraction():
    from paddle_tpu.distributed.pipeline import bubble_fraction
    # GPipe: (P-1)/(M+P-1); interleaving by V shrinks the bubble ~V-fold
    assert bubble_fraction(4, 4) == pytest.approx(3 / 7)
    assert bubble_fraction(4, 4, n_chunks=4) == pytest.approx(3 / 19)
    assert bubble_fraction(2, 8, n_chunks=2) == pytest.approx(1 / 17)


def test_interleaved_pipeline_matches_serial_low_level():
    """4 virtual stages on 2 devices (V=2), M=4 microbatches: output must
    equal the serial layer sweep (schedule + chunk layout correctness)."""
    from paddle_tpu.distributed.pipeline import pipeline_apply_hybrid
    prev = dict(mesh_mod._state)
    mesh = mesh_mod.build_mesh(dp=1, pp=2, mp=1)
    np.random.seed(0)
    D, L, P_, V = 8, 8, 2, 2
    lpc = L // (P_ * V)
    w = jnp.asarray(np.random.randn(L, D, D) * 0.1, jnp.float32)
    b = jnp.asarray(np.random.randn(L, D) * 0.1, jnp.float32)

    def block_apply(lp, h, key):
        # round-3 contract: (y, aux scalar) — aux carries MoE router losses
        return jnp.tanh(h @ lp["w"] + lp["b"]), jnp.zeros((), jnp.float32)

    # device p rows: chunk v covers virtual stage v*P+p (lpc layers each)
    order = np.asarray([(j // lpc * P_ + p) * lpc + j % lpc
                        for p in range(P_) for j in range(L // P_)])
    stacked = {"w": w[order].reshape((P_, L // P_, D, D)),
               "b": b[order].reshape((P_, L // P_, D))}
    M, mb = 4, 2
    x = jnp.asarray(np.random.randn(M, mb, D), jnp.float32)
    key = jax.random.PRNGKey(0)

    @jax.jit
    def run(stacked, x, key):
        out, _aux = pipeline_apply_hybrid(block_apply, stacked, x, key,
                                          mesh, n_stages=P_,
                                          n_microbatches=M, n_chunks=V)
        return out

    out = run(stacked, x, key)
    ref = x
    for i in range(L):
        ref = jnp.tanh(ref @ w[i] + b[i])
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)
    mesh_mod._state.update(prev)
