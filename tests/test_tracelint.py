"""tracelint: static trace-safety analyzer + registry auditor.

Covers: the rule framework (ids, severities, suppression), every rule
against a seeded-hazard corpus (each rule must fire exactly where
expected), the zero-error guarantee on the clean model-zoo corpus, the
live registry audit, `to_static(check=True)` integration (warnings
surface, semantics unchanged), the dispatch.override near-miss error,
the shard_map compat helper, and the CLI/tier-1 `--self` wiring.
"""
import ast
import inspect
import json
import os
import subprocess
import sys
import warnings

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import analysis
from paddle_tpu.analysis import core as acore
from paddle_tpu.analysis import registry_audit as raudit
from paddle_tpu.analysis.taint import TENSOR, SHAPE, UNTAINTED

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), os.pardir))


def lint(src):
    return analysis.lint_source(src, "<test>")


def rules_fired(src):
    return {f.rule for f in lint(src)}


# ===================================================================
# framework
# ===================================================================
def test_registry_has_at_least_ten_distinct_rules():
    rules = analysis.all_rules()
    assert len(rules) >= 10
    assert len({r.id for r in rules.values()}) == len(rules)
    for r in rules.values():
        assert r.severity in analysis.SEVERITIES
        assert r.id.startswith("TL")
        # a rule participates either via visitor interests or by owning
        # its own descent in finish() (e.g. TL013 walks host loops)
        assert (r.interests
                or type(r).finish is not analysis.Rule.finish), \
            f"{r.id} declares no visitor interests and no finish()"


def test_finding_shape_and_sorting():
    fs = lint("def forward(x):\n y = x.numpy()\n t = x.item()\n return t\n")
    assert [f.line for f in fs] == sorted(f.line for f in fs)
    d = fs[0].as_dict()
    assert {"file", "line", "col", "rule", "severity", "message",
            "hint", "func"} <= set(d)
    assert fs[0].func == "forward"
    assert "<test>" in fs[0].render()


def test_suppression_comment_by_id_and_blanket():
    src = ("def forward(x):\n"
           "    a = x.numpy()  # tracelint: disable=TL001\n"
           "    b = x.item()  # tracelint: disable\n"
           "    c = x.tolist()  # tracelint: disable=TL999\n"
           "    return a, b, c\n")
    fs = lint(src)
    assert [f.line for f in fs] == [4]   # only the wrong-id suppression


def test_syntax_error_is_reported_not_raised():
    fs = analysis.lint_source("def broken(:\n", "bad.py")
    assert len(fs) == 1 and fs[0].rule == "TL999"


# ===================================================================
# seeded-hazard corpus: each rule fires exactly where expected
# ===================================================================
HAZARDS = {
    "TL001": "def forward(x):\n    v = x.numpy()\n    return v\n",
    "TL002": "def forward(x):\n    return float(x.sum())\n",
    "TL003": ("import time\n"
              "def forward(x):\n    t = time.time()\n    return x * t\n"),
    "TL004": ("import numpy as np\n"
              "def forward(x):\n"
              "    return x + np.random.randn(4)\n"),
    "TL005": "def forward(x):\n    print(x)\n    return x\n",
    "TL006": ("def forward(x):\n"
              "    global STEP\n    STEP = STEP + 1\n    return x\n"),
    "TL007": ("def forward(x):\n"
              "    if x.sum() > 0:\n        return x\n"
              "    return x * 2\n"),
    "TL008": None,   # needs live closure inspection — tested separately
    "TL009": ("def forward(x,\n"
              "            scales=[1.0, 2.0]):\n"
              "    return x * scales[0]\n"),
    "TL010": ("def forward(x):\n"
              "    if x.shape[0] > 128:\n        x = x * 2\n"
              "    return x\n"),
    "TL011": ("def forward(self, x):\n"
              "    if x.mean() > 0:\n        self.cache[0] = x\n"
              "    return x\n"),
    "TL012": "def forward(x):\n    assert x.min() > 0\n    return x\n",
}


@pytest.mark.parametrize("rule_id", sorted(k for k, v in HAZARDS.items()
                                           if v is not None))
def test_each_rule_fires_on_its_seeded_hazard(rule_id):
    fs = [f for f in lint(HAZARDS[rule_id]) if f.rule == rule_id]
    assert fs, f"{rule_id} did not fire on its hazard fixture"
    # and the finding anchors to the hazardous statement, not line 1
    assert all(f.line > 1 for f in fs)


def test_seeded_hazards_fire_only_their_own_rule():
    # fixtures are minimal: no fixture may trip an unrelated ERROR rule
    for rule_id, src in HAZARDS.items():
        if src is None:
            continue
        extra = {f.rule for f in lint(src)
                 if f.severity == "error"} - {rule_id}
        assert not extra, f"{rule_id} fixture also fired {extra}"


def test_tl001_variants_and_host_path_silence():
    assert "TL001" in rules_fired(
        "def forward(x):\n    return x.tolist()\n")
    # a host-side helper (not trace-path) stays silent
    assert rules_fired(
        "def load(path):\n    return path.numpy()\n") == set()


def test_tl007_every_path_returns_form_is_allowed():
    src = ("def forward(x):\n"
           "    if x.sum() > 0:\n        return x\n"
           "    else:\n        return x * 2\n")
    assert "TL007" not in rules_fired(src)


def test_tl007_break_under_tensor_if():
    src = ("def forward(x):\n"
           "    for i in range(3):\n"
           "        if x.sum() > 0:\n            break\n"
           "        x = x + 1\n"
           "    return x\n")
    assert "TL007" in rules_fired(src)


def test_tl010_static_python_branch_is_silent():
    src = ("def forward(x, training: bool):\n"
           "    if training:\n        x = x * 2\n"
           "    return x\n")
    assert "TL010" not in rules_fired(src)


def test_lint_function_line_numbers_survive_decorators():
    """Findings from a decorated function must point at the real file
    line — co_firstlineno is the first DECORATOR line, and the source
    snippet starts there too."""
    import functools

    def deco(f):
        @functools.wraps(f)
        def inner(*a):
            return f(*a)
        return inner

    @deco
    def forward(x):
        v = x.numpy()
        return v

    target = inspect.unwrap(forward)
    hazard_line = target.__code__.co_firstlineno + 2  # decorator, def, v=
    fs = [f for f in analysis.lint_function(forward) if f.rule == "TL001"]
    assert fs and fs[0].line == hazard_line, \
        (fs, hazard_line)


def test_hazards_inside_match_cases_are_seen():
    src = ("def forward(x, mode: str):\n"
           "    match mode:\n"
           "        case 'sync':\n"
           "            y = x.numpy()\n"
           "        case _:\n"
           "            y = x * 2\n"
           "    return y\n")
    fs = lint(src)
    assert "TL001" in {f.rule for f in fs}
    assert [f.line for f in fs if f.rule == "TL001"] == [4]


def test_functions_inside_try_handlers_are_discovered():
    src = ("try:\n"
           "    import fastpath\n"
           "except ImportError:\n"
           "    def forward(x):\n"
           "        return x.numpy()\n")
    assert "TL001" in {f.rule for f in lint(src)}


def test_tl008_closure_tensor_via_lint_function():
    w = pt.ones([2, 2])

    def forward(x):
        return x.matmul(w)

    fs = analysis.lint_function(forward)
    assert "TL008" in {f.rule for f in fs}

    def clean_fn(x):
        return x * 2

    assert "TL008" not in {f.rule for f in analysis.lint_function(clean_fn)}


def test_taint_is_flow_and_annotation_aware():
    src = ("def forward(x, axis: int, flag=True):\n"
           "    n = x.shape[0]\n"
           "    y = x * 2\n"
           "    z = len(x)\n"
           "    p = x is None\n"
           "    return y\n")
    tree = ast.parse(src)
    fctx = acore.FunctionContext(tree.body[0], "<t>", "forward",
                                 trace_path=True)
    from paddle_tpu.analysis.taint import TaintPass
    env = TaintPass(fctx).run()
    assert env["x"] == TENSOR and env["y"] == TENSOR
    assert env["n"] == SHAPE and env["z"] == SHAPE
    assert env["axis"] == UNTAINTED and env["flag"] == UNTAINTED
    assert env["p"] == UNTAINTED


# ===================================================================
# clean-corpus guarantee (model zoo) + baseline self-lint
# ===================================================================
CLEAN_TARGETS = ["paddle_tpu/vision/models", "paddle_tpu/text/bert.py",
                 "paddle_tpu/text/llama.py"]


def test_model_zoo_has_zero_error_findings():
    for target in CLEAN_TARGETS:
        fs = analysis.lint_path(os.path.join(REPO, target))
        errors = [f for f in fs if f.severity == "error"]
        assert not errors, f"{target}: {[f.render() for f in errors]}"


def test_self_lint_matches_checked_in_baseline():
    from paddle_tpu.analysis import cli
    baseline = cli.load_baseline(cli.default_baseline_path())
    assert baseline, "baseline file missing or empty"
    fresh = []
    for target in cli.self_lint_targets():
        for f in analysis.lint_path(target):
            if cli.finding_key(f, REPO) not in baseline:
                fresh.append(f)
    assert not fresh, [f.render() for f in fresh]


# ===================================================================
# registry audit
# ===================================================================
def test_live_registry_audit_is_clean():
    assert raudit.audit_registry() == []


def test_audit_flags_invalid_amp_and_bad_impl():
    from paddle_tpu.ops import dispatch
    dispatch._REGISTRY["_bad_tmp"] = dispatch.OpDef(
        "_bad_tmp", lambda x: x, "sometimes")
    try:
        ids = {f.rule for f in raudit.audit_live_registry()}
        assert "REG001" in ids
    finally:
        del dispatch._REGISTRY["_bad_tmp"]


def test_audit_flags_incompatible_override_signature():
    from paddle_tpu.ops import dispatch
    dispatch.register("_sig_tmp", lambda x, alpha=1.0: x * alpha)
    try:
        dispatch.override("_sig_tmp", lambda x, *, beta: x * beta)
        ids = {f.rule for f in raudit.audit_live_registry()}
        assert "REG004" in ids
    finally:
        del dispatch._REGISTRY["_sig_tmp"]
        dispatch._OVERRIDDEN.discard("_sig_tmp")


def test_audit_source_flags_duplicate_register(tmp_path):
    pkg = tmp_path / "fake_ops"
    pkg.mkdir()
    (pkg / "a.py").write_text(
        "register('dup', lambda x: x)\n"
        "register('dup', lambda x: x * 2)\n"
        "override('missing', lambda x: x)\n"
        "register('badamp', lambda x: x, amp='fp42')\n")
    ids = {f.rule for f in raudit.audit_ops_source(str(pkg))}
    assert {"REG002", "REG003", "REG001"} <= ids


# ===================================================================
# integration: to_static(check=True) + env var + recompile cross-ref
# ===================================================================
def test_to_static_check_true_warns_and_preserves_semantics():
    class Net(pt.nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc = pt.nn.Linear(4, 4)

        def forward(self, x):
            print("tracing")
            return self.fc(x)

    net = Net()
    x = pt.randn([2, 4])
    ref = net(x)
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        st = pt.jit.to_static(net, check=True)
    assert any(issubclass(i.category, analysis.TraceLintWarning) and
               "TL005" in str(i.message) for i in w)
    np.testing.assert_allclose(np.asarray(st(x)._array),
                               np.asarray(ref._array), rtol=1e-6)


def test_to_static_check_env_var(monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_TRACELINT", "1")

    @pt.jit.not_to_static
    def f(x):
        t = x.item()
        return x

    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        pt.jit.to_static(f)
    assert any("TL001" in str(i.message) for i in w)


def test_check_false_stays_silent():
    def f(x):
        return x.item()

    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        pt.jit.to_static(f)
    assert not [i for i in w
                if issubclass(i.category, analysis.TraceLintWarning)]


def test_recompile_warning_names_static_rule():
    from paddle_tpu.observability import compile_tracker as ct
    assert analysis.static_rule_for_cause("shape change") == "TL010"
    assert analysis.static_rule_for_cause("new static arg") == "TL009"
    assert "TL010" in ct._static_rule_hint("shape change")
    assert ct._static_rule_hint("dtype change") == ""


# ===================================================================
# satellites: override near-miss, shard_map compat
# ===================================================================
def test_override_unknown_op_lists_near_misses():
    from paddle_tpu.ops import dispatch
    with pytest.raises(KeyError) as ei:
        dispatch.override("matmull", lambda a, b: a @ b)
    msg = str(ei.value)
    assert "matmull" in msg and "matmul" in msg and "registered" in msg


def test_shard_map_compat_resolves_and_runs():
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P
    from paddle_tpu.framework import compat
    devs = jax.devices()
    if len(devs) < 2:
        pytest.skip("needs >=2 devices")
    mesh = Mesh(np.array(devs[:2]), ("x",))
    f = compat.shard_map(lambda a: a * compat.axis_size("x"),
                         mesh, in_specs=P("x"), out_specs=P("x"),
                         check_vma=False)
    out = jax.jit(f)(jnp.arange(4.0))
    np.testing.assert_allclose(np.asarray(out), np.arange(4.0) * 2)


def test_shard_map_compat_partial_manual_contract():
    import jax
    from jax.sharding import Mesh, PartitionSpec as P
    from paddle_tpu.framework import compat
    devs = jax.devices()
    if len(devs) < 4:
        pytest.skip("needs >=4 devices")
    mesh = Mesh(np.array(devs[:4]).reshape(2, 2), ("pp", "dp"))
    if compat.HAS_PARTIAL_MANUAL:
        pytest.skip("native partial-manual support — no shim contract")
    with pytest.raises(NotImplementedError, match="partial-manual"):
        compat.shard_map(lambda a: a, mesh, in_specs=P("pp"),
                         out_specs=P("pp"), axis_names={"pp"})


# ===================================================================
# CLI + tier-1 --self wiring
# ===================================================================
def _run_cli(*args):
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "tracelint.py"),
         *args], capture_output=True, text=True,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))


@pytest.mark.slow
def test_cli_json_output(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text("def forward(x):\n    return x.numpy()\n")
    r = _run_cli("--json", str(bad))
    assert r.returncode == 1
    data = json.loads(r.stdout)
    assert data and data[0]["rule"] == "TL001"


def test_cli_self_inprocess():
    """The tier-1 wiring: registry audit + self-lint vs baseline must be
    green in-process (mirrors tools/trace_check.py in PR 2)."""
    import io
    from paddle_tpu.analysis import cli
    buf = io.StringIO()
    assert cli.run_self(out=buf) == 0, buf.getvalue()
    assert "registry audit OK" in buf.getvalue()


@pytest.mark.slow
def test_cli_self_subprocess():
    r = _run_cli("--self")
    assert r.returncode == 0, r.stdout + r.stderr


# ===================================================================
# TL013: loop-variant shapes in HOST decode/step loops (PR 7)
# ===================================================================
def test_tl013_fires_on_host_decode_loop_constructors():
    # constructor function-form: shape arg is args[0]
    src = ("import jax.numpy as jnp\n"
           "def decode(model, ids, b, d, max_new):\n"
           "    for t in range(max_new):\n"
           "        k = jnp.zeros((b, t + 1, d))\n"
           "        ids = model(ids, k)\n"
           "    return ids\n")
    assert "TL013" in rules_fired(src)
    # data-first function-form: the shape arg is the SECOND positional
    for call in ("jnp.broadcast_to(x, (b, t + 1, d))",
                 "jnp.tile(x, (1, t + 1))",
                 "jnp.pad(x, ((0, t), (0, 0)))",
                 "jnp.reshape(x, (b, t + 1))"):
        src = ("import jax.numpy as jnp\n"
               "def decode(x, b, d, max_new):\n"
               "    for t in range(max_new):\n"
               f"        x2 = {call}\n"
               "    return x2\n")
        assert "TL013" in rules_fired(src), call
    # method form: every positional arg is shape-ish
    src = ("def step(x, b, max_new):\n"
           "    for t in range(max_new):\n"
           "        y = x.reshape(b, t + 1)\n"
           "    return y\n")
    assert "TL013" in rules_fired(src)


def test_tl013_silent_on_safe_loops():
    # loop-invariant shapes: no storm
    src = ("import jax.numpy as jnp\n"
           "def decode(x, b, d, max_new):\n"
           "    for t in range(max_new):\n"
           "        k = jnp.zeros((b, 64, d))\n"
           "    return k\n")
    assert "TL013" not in rules_fired(src)
    # data-first function form with a loop-variant DATA arg only: the
    # output shape follows the pad widths, not the array argument
    src = ("import jax.numpy as jnp\n"
           "def decode(xs, max_new):\n"
           "    for t in range(max_new):\n"
           "        y = jnp.pad(xs[t], ((0, 4), (0, 0)))\n"
           "    return y\n")
    assert "TL013" not in rules_fired(src)
    # a loop INSIDE a trace-path function unrolls into one program
    src = ("import jax.numpy as jnp\n"
           "def forward(x, b):\n"
           "    for t in range(4):\n"
           "        x = x + jnp.zeros((b, t + 1))\n"
           "    return x\n")
    assert "TL013" not in rules_fired(src)
