"""Tensor ops numeric parity vs numpy (SURVEY §4: per-op tests)."""
import numpy as np
import pytest

import paddle_tpu as pt


def np_t(x):
    return np.asarray(x.numpy())


def test_creation():
    assert pt.zeros([2, 3]).shape == [2, 3]
    assert pt.ones([4]).numpy().sum() == 4
    assert pt.full([2, 2], 7).numpy()[0, 0] == 7
    assert pt.arange(5).tolist() == [0, 1, 2, 3, 4]
    assert pt.eye(3).numpy().trace() == 3
    assert pt.linspace(0, 1, 5).shape == [5]
    t = pt.to_tensor([[1.0, 2.0]])
    assert t.dtype == pt.float32


def test_binary_math():
    a = pt.to_tensor([1.0, 2.0, 3.0])
    b = pt.to_tensor([4.0, 5.0, 6.0])
    np.testing.assert_allclose(np_t(a + b), [5, 7, 9])
    np.testing.assert_allclose(np_t(a * b), [4, 10, 18])
    np.testing.assert_allclose(np_t(b / a), [4, 2.5, 2])
    np.testing.assert_allclose(np_t(a - 1), [0, 1, 2])
    np.testing.assert_allclose(np_t(2 ** a), [2, 4, 8])
    np.testing.assert_allclose(np_t(pt.maximum(a, b)), [4, 5, 6])


def test_matmul_shapes():
    x = pt.randn([4, 8])
    y = pt.randn([8, 3])
    assert (x @ y).shape == [4, 3]
    assert pt.matmul(x, y).shape == [4, 3]
    assert pt.matmul(y, x, transpose_x=True, transpose_y=True).shape == [3, 4]
    b1 = pt.randn([2, 4, 8])
    b2 = pt.randn([2, 8, 5])
    assert pt.bmm(b1, b2).shape == [2, 4, 5]


def test_reductions():
    x = pt.to_tensor(np.arange(12, dtype=np.float32).reshape(3, 4))
    assert float(x.sum()) == 66
    np.testing.assert_allclose(np_t(x.sum(axis=0)), [12, 15, 18, 21])
    np.testing.assert_allclose(np_t(x.mean(axis=1)),
                               np.arange(12.).reshape(3, 4).mean(1))
    assert float(x.max()) == 11
    assert float(x.min()) == 0
    assert x.sum(axis=1, keepdim=True).shape == [3, 1]
    assert int(x.argmax()) == 11
    np.testing.assert_allclose(np_t(x.std()),
                               np.arange(12.).std(ddof=1), rtol=1e-6)


def test_manipulation():
    x = pt.arange(24, dtype="float32").reshape([2, 3, 4])
    assert x.transpose([2, 0, 1]).shape == [4, 2, 3]
    assert x.flatten().shape == [24]
    assert x.flatten(1).shape == [2, 12]
    assert x.unsqueeze(0).shape == [1, 2, 3, 4]
    assert x.squeeze(None).shape == [2, 3, 4]
    parts = pt.split(x, 3, axis=1)
    assert len(parts) == 3 and parts[0].shape == [2, 1, 4]
    parts = pt.split(x, [1, -1], axis=2)
    assert parts[1].shape == [2, 3, 3]
    c = pt.concat([x, x], axis=0)
    assert c.shape == [4, 3, 4]
    s = pt.stack([x, x], axis=0)
    assert s.shape == [2, 2, 3, 4]
    assert pt.tile(pt.ones([2]), [3]).shape == [6]
    assert pt.flip(pt.arange(3), axis=0).tolist() == [2, 1, 0]


def test_indexing():
    x = pt.arange(12, dtype="float32").reshape([3, 4])
    assert float(x[1, 2]) == 6
    assert x[0].shape == [4]
    assert x[:, 1:3].shape == [3, 2]
    idx = pt.to_tensor([0, 2])
    assert pt.index_select(x, idx, axis=0).shape == [2, 4]
    assert pt.gather(x, idx, axis=1).shape == [3, 2]
    y = pt.zeros([3, 3])
    y[1, 1] = 5.0
    assert float(y[1, 1]) == 5.0


def test_comparison_and_logic():
    a = pt.to_tensor([1.0, 2.0, 3.0])
    b = pt.to_tensor([3.0, 2.0, 1.0])
    assert np_t(a == b).tolist() == [False, True, False]
    assert np_t(a < b).tolist() == [True, False, False]
    assert bool(pt.allclose(a, a))
    assert not bool(pt.allclose(a, b))
    assert bool(pt.equal_all(a, a))


def test_sort_topk():
    x = pt.to_tensor([3.0, 1.0, 2.0])
    assert np_t(pt.sort(x)).tolist() == [1, 2, 3]
    assert np_t(pt.argsort(x)).tolist() == [1, 2, 0]
    v, i = pt.topk(x, 2)
    assert np_t(v).tolist() == [3, 2]
    assert np_t(i).tolist() == [0, 2]


def test_where_masking():
    x = pt.to_tensor([1.0, -2.0, 3.0])
    out = pt.where(x > 0, x, pt.zeros_like(x))
    assert np_t(out).tolist() == [1, 0, 3]
    mf = pt.masked_fill(x, x < 0, 0.0)
    assert np_t(mf).tolist() == [1, 0, 3]


def test_einsum():
    a = pt.randn([3, 4])
    b = pt.randn([4, 5])
    out = pt.einsum("ij,jk->ik", a, b)
    np.testing.assert_allclose(np_t(out), np_t(a) @ np_t(b), rtol=1e-5)


def test_linalg():
    a = np.array([[2.0, 0.0], [0.0, 4.0]], np.float32)
    x = pt.to_tensor(a)
    np.testing.assert_allclose(np_t(pt.linalg.inv(x)), np.linalg.inv(a),
                               rtol=1e-5)
    np.testing.assert_allclose(float(pt.linalg.det(x)), 8.0, rtol=1e-5)
    q, r = pt.linalg.qr(x)
    np.testing.assert_allclose(np_t(q.matmul(r)), a, atol=1e-5)


def test_dtype_cast():
    x = pt.to_tensor([1.5, 2.5])
    assert x.astype("int32").dtype == pt.int32
    assert x.astype(pt.bfloat16).dtype == pt.bfloat16
    assert pt.cast(x, "float16").dtype == pt.float16


def test_cumsum_cumprod():
    x = pt.to_tensor([1.0, 2.0, 3.0])
    assert np_t(pt.cumsum(x, axis=0)).tolist() == [1, 3, 6]
    assert np_t(pt.cumprod(x, dim=0)).tolist() == [1, 2, 6]


def test_pad_roll():
    x = pt.ones([2, 2])
    # len(pad) == 2*ndim → per-dim [d0_lo, d0_hi, d1_lo, d1_hi]
    p = pt.pad(x, [1, 1, 0, 0])
    assert p.shape == [4, 2]
    # shorter form pads trailing dims (reference/torch convention)
    x3 = pt.ones([2, 3, 4])
    assert pt.pad(x3, [1, 1]).shape == [2, 3, 6]
    r = pt.roll(pt.arange(4), 1)
    assert np_t(r).tolist() == [3, 0, 1, 2]


def test_broadcast_expand():
    x = pt.ones([1, 3])
    assert pt.expand(x, [4, 3]).shape == [4, 3]
    assert pt.broadcast_to(x, [2, 3]).shape == [2, 3]
