"""paddle.vision.ops tests (nms/roi_align/roi_pool/box_coder vs
hand-computed references)."""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.vision import ops as vops


def test_box_iou():
    a = pt.to_tensor(np.array([[0, 0, 2, 2], [1, 1, 3, 3]], np.float32))
    iou = vops.box_iou(a, a).numpy()
    np.testing.assert_allclose(np.diag(iou), [1.0, 1.0], rtol=1e-5)
    np.testing.assert_allclose(iou[0, 1], 1 / 7, rtol=1e-4)


def test_nms_basic():
    boxes = pt.to_tensor(np.array([
        [0, 0, 10, 10],      # score .9  kept
        [1, 1, 11, 11],      # score .8  suppressed by 0 (iou ~ .68)
        [20, 20, 30, 30],    # score .7  kept (disjoint)
        [0, 0, 10, 10],      # score .1  suppressed by 0
    ], np.float32))
    scores = pt.to_tensor(np.array([0.9, 0.8, 0.7, 0.1], np.float32))
    keep = vops.nms(boxes, scores, iou_threshold=0.5).numpy()
    np.testing.assert_array_equal(keep, [0, 2])


def test_nms_categories_and_topk():
    boxes = pt.to_tensor(np.array([
        [0, 0, 10, 10], [1, 1, 11, 11], [0, 0, 10, 10],
    ], np.float32))
    scores = pt.to_tensor(np.array([0.9, 0.8, 0.7], np.float32))
    cidx = pt.to_tensor(np.array([0, 1, 0]))
    # classes 0 and 1 don't suppress each other
    keep = vops.nms(boxes, scores, iou_threshold=0.5,
                    category_idxs=cidx, categories=[0, 1]).numpy()
    np.testing.assert_array_equal(keep, [0, 1])
    keep = vops.nms(boxes, scores, iou_threshold=0.5,
                    category_idxs=cidx, categories=[0, 1], top_k=1).numpy()
    np.testing.assert_array_equal(keep, [0])


def test_roi_align_identity():
    """RoI covering one exact cell center grid reproduces bilinear values."""
    H = W = 4
    x = pt.to_tensor(np.arange(H * W, dtype=np.float32).reshape(1, 1, H, W))
    boxes = pt.to_tensor(np.array([[0, 0, 4, 4]], np.float32))
    out = vops.roi_align(x, boxes, pt.to_tensor(np.array([1])),
                         output_size=4, sampling_ratio=1, aligned=True)
    assert tuple(out.shape) == (1, 1, 4, 4)
    # sampling points hit exact pixel centers -> identity
    np.testing.assert_allclose(out.numpy()[0, 0], x.numpy()[0, 0],
                               rtol=1e-4)


def test_roi_align_multi_batch_routing():
    x = np.zeros((2, 1, 4, 4), np.float32)
    x[0] += 1.0
    x[1] += 5.0
    boxes = pt.to_tensor(np.array([[0, 0, 4, 4], [0, 0, 4, 4]], np.float32))
    out = vops.roi_align(pt.to_tensor(x), boxes,
                         pt.to_tensor(np.array([1, 1])), output_size=2)
    np.testing.assert_allclose(out.numpy()[0], 1.0, rtol=1e-5)
    np.testing.assert_allclose(out.numpy()[1], 5.0, rtol=1e-5)


def test_roi_pool_max():
    x = pt.to_tensor(np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4))
    boxes = pt.to_tensor(np.array([[0, 0, 4, 4]], np.float32))
    out = vops.roi_pool(x, boxes, pt.to_tensor(np.array([1])),
                        output_size=2)
    np.testing.assert_allclose(out.numpy()[0, 0], [[5, 7], [13, 15]])


def test_box_coder_roundtrip():
    priors = pt.to_tensor(np.array([[0, 0, 10, 10], [5, 5, 15, 15]],
                                   np.float32))
    targets = pt.to_tensor(np.array([[1, 1, 9, 9], [6, 4, 14, 16]],
                                    np.float32))
    enc = vops.box_coder(priors, None, targets,
                         code_type="encode_center_size")
    dec = vops.box_coder(priors, None, enc,
                         code_type="decode_center_size", axis=1)
    # decode(encode(t)) against each prior's own row reproduces the target
    d = dec.numpy()
    np.testing.assert_allclose(d[0, 0], targets.numpy()[0], atol=1e-4)
    np.testing.assert_allclose(d[1, 1], targets.numpy()[1], atol=1e-4)


def test_roi_align_differentiable():
    x = pt.to_tensor(np.random.RandomState(0).randn(1, 2, 8, 8)
                     .astype(np.float32))
    x.stop_gradient = False
    boxes = pt.to_tensor(np.array([[1, 1, 6, 6]], np.float32))
    out = vops.roi_align(x, boxes, pt.to_tensor(np.array([1])),
                         output_size=3)
    out.sum().backward()
    assert x.grad is not None and np.abs(x.grad.numpy()).sum() > 0


def test_roi_pool_out_of_bounds_clamps():
    x = pt.to_tensor(np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4))
    boxes = pt.to_tensor(np.array([[0, 0, 4, 8]], np.float32))  # past H
    out = vops.roi_pool(x, boxes, pt.to_tensor(np.array([1])),
                        output_size=2).numpy()
    assert out.min() >= 0.0  # no -inf sentinel leaks


def test_reshard_keeps_gradient():
    import paddle_tpu.distributed as dist
    mesh = dist.ProcessMesh(list(range(8)), dim_names=["x"])
    x = pt.randn([8, 4]); x.stop_gradient = False
    y = dist.reshard(x, mesh, [dist.Shard(0)])
    (y ** 2).sum().backward()
    assert x.grad is not None
    np.testing.assert_allclose(x.grad.numpy(), 2 * x.numpy(), rtol=1e-5)


def test_box_coder_single_box_rank():
    priors = pt.to_tensor(np.array([[0, 0, 10, 10]], np.float32))
    targets = pt.to_tensor(np.array([[1, 1, 9, 9]], np.float32))
    enc = vops.box_coder(priors, None, targets)
    assert tuple(enc.shape) == (1, 1, 4)
    dec = vops.box_coder(priors, None, enc, code_type="decode_center_size")
    assert tuple(dec.shape) == (1, 1, 4)  # rank stable even at N=M=1
