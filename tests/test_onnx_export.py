"""paddle.onnx.export — real ONNX serialization + round-trip execution.

Each test exports a live layer, re-loads the .onnx protobuf, executes it
with the bundled reference evaluator (paddle_tpu/onnx/runtime.py — an
independent numpy implementation of the ONNX operator spec), and compares
against the layer's own forward.  That validates graph topology, attrs,
initializers, and the wire format end to end without onnxruntime.
"""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import onnx as ponnx


def _roundtrip(layer, examples, tmp_path, rtol=1e-4, atol=1e-5):
    layer.eval()
    with pt.no_grad():
        want = layer(*examples)
    want = [t.numpy() for t in (want if isinstance(want, (tuple, list))
                                else [want])]
    path = ponnx.export(layer, str(tmp_path / "model"), input_spec=examples)
    model = ponnx.load(path)
    got = ponnx.run(model, [t.numpy() for t in examples])
    assert len(got) == len(want)
    for g, w in zip(got, want):
        np.testing.assert_allclose(g, w, rtol=rtol, atol=atol)
    return model


class TestOnnxExport:
    def test_mlp_roundtrip(self, tmp_path):
        pt.seed(0)
        m = pt.nn.Sequential(
            pt.nn.Linear(8, 32), pt.nn.ReLU(), pt.nn.LayerNorm(32),
            pt.nn.Linear(32, 16), pt.nn.GELU(), pt.nn.Linear(16, 4),
            pt.nn.Softmax())
        model = _roundtrip(m, [pt.rand([3, 8])], tmp_path)
        ops = [n.op_type for n in model.graph.node]
        assert "MatMul" in ops and "LayerNormalization" in ops \
            and "Erf" in ops and "Softmax" in ops
        assert model.opset_import[0].version == 17

    def test_convnet_roundtrip(self, tmp_path):
        pt.seed(1)
        m = pt.nn.Sequential(
            pt.nn.Conv2D(3, 8, 3, stride=2, padding=1),
            pt.nn.BatchNorm2D(8), pt.nn.ReLU(),
            pt.nn.MaxPool2D(2, stride=2),
            pt.nn.AdaptiveAvgPool2D((1, 1)),
            pt.nn.Flatten(), pt.nn.Linear(8, 5))
        model = _roundtrip(m, [pt.rand([2, 3, 16, 16])], tmp_path)
        ops = [n.op_type for n in model.graph.node]
        assert "Conv" in ops and "BatchNormalization" in ops \
            and "MaxPool" in ops and "GlobalAveragePool" in ops

    def test_resnet18_roundtrip(self, tmp_path):
        pt.seed(2)
        from paddle_tpu.vision.models import resnet18
        with pt.LazyGuard():
            m = resnet18(num_classes=10)
        _roundtrip(m, [pt.rand([1, 3, 32, 32])], tmp_path,
                   rtol=5e-3, atol=5e-4)

    def test_bert_tiny_roundtrip(self, tmp_path):
        pt.seed(3)
        from paddle_tpu.text.bert import BertConfig, BertModel
        cfg = BertConfig(vocab_size=64, hidden_size=32, num_hidden_layers=2,
                         num_attention_heads=2, intermediate_size=64,
                         max_position_embeddings=32)
        m = BertModel(cfg)
        ids = pt.to_tensor(np.arange(8, dtype=np.int64)[None, :] % 64)
        model = _roundtrip(m, [ids], tmp_path, rtol=1e-3, atol=1e-4)
        ops = [n.op_type for n in model.graph.node]
        assert "Gather" in ops and "Softmax" in ops   # embedding + sdpa

    def test_gpt_tiny_roundtrip(self, tmp_path):
        pt.seed(4)
        from paddle_tpu.text import GPTConfig, GPTForCausalLM
        cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=2,
                        num_heads=4, max_position_embeddings=16,
                        hidden_dropout=0.0, attention_dropout=0.0)
        m = GPTForCausalLM(cfg)
        ids = pt.to_tensor(np.arange(16, dtype=np.int64)[None, :] % 64)
        model = _roundtrip(m, [ids], tmp_path, rtol=1e-3, atol=1e-4)
        ops = [n.op_type for n in model.graph.node]
        assert "Split" in ops     # fused qkv unbind

    def test_llama_gqa_rope_roundtrip(self, tmp_path):
        # covers rms_norm/silu/rope decompositions + GQA head tiling
        pt.seed(5)
        from paddle_tpu.text.llama import LlamaConfig, LlamaForCausalLM
        cfg = LlamaConfig(vocab_size=64, hidden_size=32, num_layers=2,
                          num_heads=4, num_kv_heads=2, intermediate_size=64,
                          max_position_embeddings=16)
        m = LlamaForCausalLM(cfg)
        ids = pt.to_tensor(np.arange(16, dtype=np.int64)[None, :] % 64)
        model = _roundtrip(m, [ids], tmp_path, rtol=1e-3, atol=1e-4)
        ops = [n.op_type for n in model.graph.node]
        assert "Tile" in ops and "Erf" not in ops   # GQA; silu not gelu

    def test_tanh_gelu_honored(self, tmp_path):
        # GPT uses approximate (tanh) gelu; the emitter must not silently
        # substitute erf-gelu (~5e-4 deviation per activation)
        class G(pt.nn.Layer):
            def forward(self, x):
                return pt.nn.functional.gelu(x, approximate=True)

        x = pt.to_tensor(np.linspace(-3, 3, 64, dtype=np.float32)
                         .reshape(8, 8))
        model = _roundtrip(G(), [x], tmp_path, rtol=1e-6, atol=1e-6)
        ops = [n.op_type for n in model.graph.node]
        assert "Tanh" in ops and "Erf" not in ops

    def test_ceil_mode_pool_roundtrip(self, tmp_path):
        # 6x6 with k=3 s=2: floor gives 2, ceil gives 3 — the sizes
        # diverge, so this actually exercises the evaluator's ceil branch
        m = pt.nn.MaxPool2D(3, stride=2, ceil_mode=True)
        _roundtrip(m, [pt.rand([1, 2, 6, 6])], tmp_path)

    def test_negative_step_slice_raises(self, tmp_path):
        class R(pt.nn.Layer):
            def forward(self, x):
                return x[::-1] * 1.0

        with pytest.raises(NotImplementedError, match="negative-step"):
            ponnx.export(R(), str(tmp_path / "rev"),
                         input_spec=[pt.rand([4, 3])])

    def test_opset_18_rejected(self, tmp_path):
        with pytest.raises(NotImplementedError, match="opset"):
            ponnx.export(pt.nn.Linear(2, 2), str(tmp_path / "o18"),
                         input_spec=[pt.rand([1, 2])], opset_version=18)

    def test_gpt_dynamic_batch(self, tmp_path):
        # Reshape targets emit batch as 0 ("copy from input"), so a graph
        # traced at batch 1 with a dim_param input runs at any batch
        pt.seed(6)
        from paddle_tpu.static import InputSpec
        from paddle_tpu.text import GPTConfig, GPTForCausalLM
        cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=1,
                        num_heads=4, max_position_embeddings=8,
                        hidden_dropout=0.0, attention_dropout=0.0)
        m = GPTForCausalLM(cfg)
        path = ponnx.export(m, str(tmp_path / "gptdyn"),
                            input_spec=[InputSpec([None, 8], "int64")])
        ids = np.random.RandomState(0).randint(0, 64, (3, 8)).astype(np.int64)
        got = ponnx.run(path, [ids])[0]
        m.eval()
        with pt.no_grad():
            want = m(pt.to_tensor(ids)).numpy()
        np.testing.assert_allclose(got, want, rtol=1e-3, atol=1e-4)

    def test_ernie_cls_roundtrip(self, tmp_path):
        pt.seed(7)
        from paddle_tpu.text.ernie import (ErnieConfig,
                                           ErnieForSequenceClassification)
        cfg = ErnieConfig(vocab_size=64, hidden_size=32,
                          num_hidden_layers=2, num_attention_heads=2,
                          intermediate_size=64, max_position_embeddings=32)
        m = ErnieForSequenceClassification(cfg, num_classes=3)
        ids = pt.to_tensor(np.arange(8, dtype=np.int64)[None, :] % 64)
        _roundtrip(m, [ids], tmp_path, rtol=1e-3, atol=1e-4)

    def test_seq2seq_mt_roundtrip(self, tmp_path):
        # encoder-decoder with cross-attention (masked sdpa decomposition)
        pt.seed(8)
        from paddle_tpu.text.transformer_mt import TransformerModel
        m = TransformerModel(src_vocab_size=64, trg_vocab_size=64,
                             max_length=16, num_encoder_layers=1,
                             num_decoder_layers=1, n_head=2, d_model=32,
                             d_inner_hid=64, dropout=0.0)
        src = pt.to_tensor(np.arange(8, dtype=np.int64)[None, :] % 64)
        trg = pt.to_tensor((np.arange(8, dtype=np.int64)[None, :] + 1) % 64)
        _roundtrip(m, [src, trg], tmp_path, rtol=1e-3, atol=1e-4)

    def test_split_with_infer_section(self, tmp_path):
        # paddle.split(x, [2, -1], axis=1): the -1 must be resolved before
        # serialization (ONNX Split rejects negative section lengths)
        class S(pt.nn.Layer):
            def forward(self, x):
                a, b = pt.split(x, [2, -1], axis=1)
                return a.sum(axis=1, keepdim=True) + b.sum(axis=1,
                                                           keepdim=True)

        model = _roundtrip(S(), [pt.rand([3, 6])], tmp_path)
        split_init = [i for i in model.graph.initializer
                      if i.name.startswith("split")]
        assert split_init and all(
            v >= 0 for v in np.frombuffer(split_init[0].raw_data, np.int64))

    def test_unsupported_op_raises_with_name(self, tmp_path):
        class Odd(pt.nn.Layer):
            def forward(self, x):
                return pt.cumsum(x, axis=0)

        with pytest.raises(NotImplementedError, match="cumsum"):
            ponnx.export(Odd(), str(tmp_path / "odd"),
                         input_spec=[pt.rand([3, 3])])

    def test_input_spec_dynamic_batch(self, tmp_path):
        from paddle_tpu.static import InputSpec
        m = pt.nn.Linear(4, 2)
        path = ponnx.export(m, str(tmp_path / "dyn"),
                            input_spec=[InputSpec([None, 4], "float32")])
        model = ponnx.load(path)
        d0 = model.graph.input[0].type.tensor_type.shape.dim[0]
        assert d0.dim_param == "dyn_0"
        # evaluator executes at any batch
        out = ponnx.run(model, [np.random.randn(7, 4).astype(np.float32)])
        assert out[0].shape == (7, 2)


def test_qwen2_roundtrip(tmp_path):
    """Qwen2 (biased q/k/v llama block) exports and re-evaluates."""
    from paddle_tpu.text import Qwen2Config, Qwen2ForCausalLM
    pt.seed(0)
    m = Qwen2ForCausalLM(Qwen2Config.from_preset(
        "qwen2-tiny", tensor_parallel=False))
    m.eval()
    ids = pt.randint(0, 256, [2, 12])
    want = np.asarray(m(ids)._array)
    from paddle_tpu.static import InputSpec
    path = ponnx.export(m, str(tmp_path / "qwen2"),
                        input_spec=[InputSpec([2, 12], "int64",
                                              "input_ids")])
    got = ponnx.run(path, {"input_ids": np.asarray(ids._array)})[0]
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)
