"""Round-2 tensor-API additions vs numpy (SURVEY §2 Tensor methods)."""
import numpy as np

import paddle_tpu as pt
from paddle_tpu import tensor_api as T


def _x(seed=0, shape=(3, 4)):
    return pt.to_tensor(np.random.RandomState(seed).randn(
        *shape).astype(np.float32))


def test_trapezoid_nanquantile_bucketize():
    x = _x()
    np.testing.assert_allclose(T.trapezoid(x).numpy(),
                               np.trapezoid(x.numpy(), axis=-1), rtol=1e-5)
    assert T.nanquantile(x, 0.5).shape == []
    b = T.bucketize(pt.to_tensor(np.array([0.1, 2.5], np.float32)),
                    pt.to_tensor(np.array([0., 1., 2., 3.], np.float32)))
    np.testing.assert_array_equal(b.numpy(), [1, 3])


def test_unique_consecutive():
    u, inv, cnt = T.unique_consecutive(
        pt.to_tensor(np.array([1, 1, 2, 2, 2, 3, 1], np.int32)),
        return_inverse=True, return_counts=True)
    np.testing.assert_array_equal(u.numpy(), [1, 2, 3, 1])
    np.testing.assert_array_equal(inv.numpy(), [0, 0, 1, 1, 1, 2, 3])
    np.testing.assert_array_equal(cnt.numpy(), [2, 3, 1, 1])


def test_take_renorm_msort():
    x = _x()
    np.testing.assert_array_equal(
        T.take(x, pt.to_tensor(np.array([0, 5], np.int32))).numpy(),
        x.numpy().reshape(-1)[[0, 5]])
    r = T.renorm(x, p=2.0, axis=0, max_norm=1.0)
    assert np.linalg.norm(r.numpy(), axis=1).max() <= 1.0 + 1e-5
    np.testing.assert_array_equal(T.msort(x).numpy(),
                                  np.sort(x.numpy(), axis=0))


def test_int_and_float_bit_ops():
    np.testing.assert_array_equal(
        T.gcd(pt.to_tensor(np.array([12], np.int32)),
              pt.to_tensor(np.array([18], np.int32))).numpy(), [6])
    np.testing.assert_array_equal(
        T.lcm(pt.to_tensor(np.array([4], np.int32)),
              pt.to_tensor(np.array([6], np.int32))).numpy(), [12])
    m, e = T.frexp(pt.to_tensor(np.array([8.0], np.float32)))
    np.testing.assert_allclose(m.numpy() * 2.0 ** e.numpy(), [8.0])
    np.testing.assert_allclose(
        T.ldexp(pt.to_tensor(np.array([1.5], np.float32)),
                pt.to_tensor(np.array([3], np.int32))).numpy(), [12.0])
    assert T.signbit(pt.to_tensor(
        np.array([-1.0, 2.0], np.float32))).numpy().tolist() == [True, False]


def test_shape_manipulation():
    x = _x()
    assert T.view_as(x, pt.zeros([4, 3])).shape == [4, 3]
    assert T.unflatten(pt.zeros([2, 12]), 1, [3, 4]).shape == [2, 3, 4]
    assert T.moveaxis(pt.zeros([2, 3, 4]), 0, -1).shape == [3, 4, 2]
    assert T.vander(pt.to_tensor(
        np.array([1.0, 2.0], np.float32))).shape == [2, 2]


def test_tensordot_grad_and_histogramdd():
    rng = np.random.RandomState(1)
    g = pt.to_tensor(rng.randn(3, 4).astype(np.float32))
    g.stop_gradient = False
    y = pt.to_tensor(rng.randn(4, 2).astype(np.float32))
    out = T.tensordot(g, y, axes=1)
    assert out.shape == [3, 2]
    out.sum().backward()
    np.testing.assert_allclose(g.grad.numpy(),
                               np.tile(y.numpy().sum(1), (3, 1)),
                               rtol=1e-5)
    h, edges = T.histogramdd(pt.to_tensor(
        rng.randn(20, 2).astype(np.float32)), bins=4)
    assert h.shape == [4, 4] and len(edges) == 2
    assert float(h.numpy().sum()) == 20.0


def test_complex_and_angles():
    p = T.polar(pt.to_tensor(np.array([1.0], np.float32)),
                pt.to_tensor(np.array([np.pi / 2], np.float32)))
    np.testing.assert_allclose(p.numpy().imag, [1.0], atol=1e-6)
    np.testing.assert_allclose(T.angle(p).numpy(), [np.pi / 2], rtol=1e-5)
    np.testing.assert_allclose(
        T.deg2rad(pt.to_tensor(np.array([180.0], np.float32))).numpy(),
        [np.pi], rtol=1e-6)
    np.testing.assert_allclose(
        T.rad2deg(pt.to_tensor(np.array([np.pi], np.float32))).numpy(),
        [180.0], rtol=1e-6)
    assert T.isneginf(pt.to_tensor(
        np.array([-np.inf, 1.0], np.float32))).numpy().tolist() == \
        [True, False]
