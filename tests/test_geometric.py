"""paddle.geometric (reference: python/paddle/geometric) — segment
reductions + message passing, values vs numpy and gradients."""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import geometric as G


def test_segment_reductions_match_numpy():
    rng = np.random.RandomState(0)
    x = rng.randn(10, 4).astype(np.float32)
    ids = np.array([0, 0, 1, 1, 1, 2, 2, 3, 3, 3], np.int32)
    xt, it = pt.to_tensor(x), pt.to_tensor(ids)
    for op, ref in [
        (G.segment_sum, lambda rows: rows.sum(0)),
        (G.segment_mean, lambda rows: rows.mean(0)),
        (G.segment_max, lambda rows: rows.max(0)),
        (G.segment_min, lambda rows: rows.min(0)),
    ]:
        out = op(xt, it).numpy()
        want = np.stack([ref(x[ids == s]) for s in range(4)])
        np.testing.assert_allclose(out, want, rtol=1e-6, atol=1e-6)


def test_segment_sum_gradient():
    x = pt.to_tensor(np.ones((6, 2), np.float32))
    x.stop_gradient = False
    ids = pt.to_tensor(np.array([0, 1, 1, 2, 2, 2], np.int32))
    out = G.segment_sum(x, ids)
    (out * pt.to_tensor(np.array([[1.], [2.], [3.]], np.float32))).sum() \
        .backward()
    # grad of segment_sum is a gather of the upstream cotangent
    want = np.array([[1, 1], [2, 2], [2, 2], [3, 3], [3, 3], [3, 3]],
                    np.float32)
    np.testing.assert_allclose(x.grad.numpy(), want)


def test_segment_empty_segment_emits_zero():
    x = pt.to_tensor(np.ones((2, 3), np.float32))
    ids = pt.to_tensor(np.array([0, 2], np.int32))
    out = G.segment_max(x, ids).numpy()
    assert out.shape == (3, 3)
    np.testing.assert_allclose(out[1], 0.0)


def test_send_u_recv():
    x = pt.to_tensor(np.arange(8, dtype=np.float32).reshape(4, 2))
    src = pt.to_tensor(np.array([0, 1, 2, 3], np.int32))
    dst = pt.to_tensor(np.array([1, 1, 3, 3], np.int32))
    out = G.send_u_recv(x, src, dst, reduce_op="sum").numpy()
    want = np.zeros((4, 2), np.float32)
    want[1] = x.numpy()[0] + x.numpy()[1]
    want[3] = x.numpy()[2] + x.numpy()[3]
    np.testing.assert_allclose(out, want)


def test_send_ue_recv_mul():
    x = pt.to_tensor(np.ones((3, 2), np.float32))
    y = pt.to_tensor(np.array([[2.0, 2.0], [3.0, 3.0]], np.float32))
    src = pt.to_tensor(np.array([0, 1], np.int32))
    dst = pt.to_tensor(np.array([2, 2], np.int32))
    out = G.send_ue_recv(x, y, src, dst, message_op="mul",
                         reduce_op="sum").numpy()
    np.testing.assert_allclose(out[2], [5.0, 5.0])


def test_segment_out_size_under_jit():
    import jax
    import jax.numpy as jnp
    from paddle_tpu.ops.dispatch import call_raw

    def f(x, ids):
        return call_raw("segment_sum", x, ids, n=4)

    out = jax.jit(f)(jnp.ones((5, 2)), jnp.array([0, 1, 1, 3, 3]))
    assert out.shape == (4, 2)
