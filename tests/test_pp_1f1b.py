"""1F1B pipeline schedule (VERDICT r3 item 4; reference: fleet
meta_parallel pipeline_parallel.py's 1F1B).

The 1F1B path is a hand-written two-scan custom_vjp (pipeline.py
onef1b_pipeline): forward GPipe wave storing only [M, mb] stage-boundary
inputs, backward wave recomputing each stage with jax.vjp.  These tests
pin (a) exact-math parity with the differentiable GPipe scan across
pp degrees, MoE, and dp composition, and (b) the memory claim: compiled
temp bytes strictly below the GPipe scan's and below the 1F1B analytic
activation budget."""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.distributed import fleet
from paddle_tpu.distributed import mesh as mesh_mod



@pytest.fixture
def restore_mesh():
    prev = dict(mesh_mod._state)
    yield
    mesh_mod._state.update(prev)


def _gpt(seed=0, layers=4, moe=False):
    from paddle_tpu.text import GPTConfig, GPTForCausalLM
    pt.seed(seed)
    kw = {}
    if moe:
        kw = dict(num_experts=4, moe_capacity_factor=4.0)
    cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=layers,
                    num_heads=4, max_position_embeddings=32,
                    hidden_dropout=0.0, attention_dropout=0.0,
                    tensor_parallel=False, **kw)
    return GPTForCausalLM(cfg)


def _train(sched, pp, M, dp=1, moe=False, steps=3, seed=0, layers=4,
           vpp=1):
    """Build + train a few steps under `sched`; return (losses, state)."""
    from paddle_tpu.text import gpt_loss_fn
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": dp, "mp_degree": 1,
                               "pp_degree": pp, "accumulate_steps": M,
                               "pp_schedule": sched,
                               "virtual_pp_degree": vpp}
    fleet.init(is_collective=True, strategy=strategy)
    m = _gpt(seed=seed, layers=layers, moe=moe)
    opt = pt.optimizer.Adam(learning_rate=0.02, parameters=m.parameters())
    step = fleet.build_train_step(m, gpt_loss_fn, opt)
    pt.seed(7)
    ids = pt.randint(0, 64, [8, 16])
    labels = pt.randint(0, 64, [8, 16])
    losses = [float(step(ids, labels)) for _ in range(steps)]
    step.sync_model()
    sd = {k: np.asarray(v._array) for k, v in m.state_dict().items()}
    return losses, sd


def _assert_parity(restore_mesh, pp, M, dp=1, moe=False, layers=4,
                   vpp=1):
    prev = dict(mesh_mod._state)
    l_ref, sd_ref = _train("F-then-B", pp, M, dp=dp, moe=moe,
                           layers=layers, vpp=1)
    mesh_mod._state.update(prev)
    l_1f, sd_1f = _train("1F1B", pp, M, dp=dp, moe=moe, layers=layers,
                         vpp=vpp)
    assert np.allclose(l_ref, l_1f, rtol=3e-4, atol=3e-5), \
        f"loss mismatch: {l_ref} vs {l_1f}"
    worst = max(float(np.max(np.abs(sd_ref[k] - sd_1f[k])))
                for k in sd_ref)
    assert worst < 5e-4, f"param divergence {worst}"


def test_1f1b_matches_gpipe_pp2(restore_mesh):
    _assert_parity(restore_mesh, pp=2, M=4)


def test_1f1b_matches_gpipe_pp4(restore_mesh):
    _assert_parity(restore_mesh, pp=4, M=4, layers=8)


@pytest.mark.xfail(
    strict=False,
    reason="pre-existing at seed: old-shard_map transpose (_SpecError) "
           "under jax 0.4.37 via framework/compat.py; unblocks with the "
           "ROADMAP item-3c migration off the compat shims")
def test_1f1b_matches_gpipe_moe(restore_mesh):
    """Router aux losses (and their gradients) ride the custom bwd via the
    daux cotangent — parity must hold including the aux term."""
    _assert_parity(restore_mesh, pp=2, M=2, moe=True)


@pytest.mark.needs_partial_manual
def test_1f1b_matches_gpipe_dp_x_pp(restore_mesh):
    """dp stays a GSPMD annotation inside the partial-manual shard_map in
    both the forward AND the hand-written backward."""
    _assert_parity(restore_mesh, pp=2, M=2, dp=2)


def test_interleaved_1f1b_matches_gpipe(restore_mesh):
    """vpp=2 x 1F1B (Megatron's interleaved 1F1B as a two-scan
    custom_vjp): chunk waves + mirrored grad FIFO must reproduce the
    plain differentiable schedule's math exactly."""
    _assert_parity(restore_mesh, pp=2, M=4, layers=4, vpp=2)


def test_interleaved_1f1b_matches_gpipe_pp2_vpp2_deep(restore_mesh):
    _assert_parity(restore_mesh, pp=2, M=4, layers=8, vpp=2)


def test_1f1b_is_default_schedule(restore_mesh):
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 1, "mp_degree": 1,
                               "pp_degree": 2, "accumulate_steps": 4}
    fleet.init(is_collective=True, strategy=strategy)
    from paddle_tpu.text import gpt_loss_fn
    m = _gpt()
    opt = pt.optimizer.SGD(learning_rate=0.01, parameters=m.parameters())
    step = fleet.build_train_step(m, gpt_loss_fn, opt)
    assert step.pp_schedule == "1F1B"
    # vpp>1 also defaults to 1F1B (interleaved wave); F-then-B on request
    strategy2 = fleet.DistributedStrategy()
    strategy2.hybrid_configs = {"dp_degree": 1, "mp_degree": 1,
                                "pp_degree": 2, "accumulate_steps": 4,
                                "virtual_pp_degree": 2}
    fleet.init(is_collective=True, strategy=strategy2)
    m2 = _gpt()
    opt2 = pt.optimizer.SGD(learning_rate=0.01, parameters=m2.parameters())
    step2 = fleet.build_train_step(m2, gpt_loss_fn, opt2)
    assert step2.pp_schedule == "1F1B"
    strategy3 = fleet.DistributedStrategy()
    strategy3.hybrid_configs = {"dp_degree": 1, "mp_degree": 1,
                                "pp_degree": 2, "accumulate_steps": 4,
                                "pp_schedule": "F-then-B"}
    fleet.init(is_collective=True, strategy=strategy3)
    m3 = _gpt()
    opt3 = pt.optimizer.SGD(learning_rate=0.01, parameters=m3.parameters())
    step3 = fleet.build_train_step(m3, gpt_loss_fn, opt3)
    assert step3.pp_schedule == "FTHENB"


def test_1f1b_full_step_memory_below_gpipe(restore_mesh):
    """Whole fused train step: 1F1B's compiled temp bytes must undercut
    the differentiable GPipe scan's at the same config."""
    from paddle_tpu.text import gpt_loss_fn
    P, M = 2, 8
    hidden, seq, batch, layers, heads = 64, 64, 16, 4, 4
    temps = {}
    for sched in ("F-then-B", "1F1B"):
        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs = {"dp_degree": 1, "mp_degree": 1,
                                   "pp_degree": P, "accumulate_steps": M,
                                   "pp_schedule": sched}
        fleet.init(is_collective=True, strategy=strategy)
        from paddle_tpu.text import GPTConfig, GPTForCausalLM
        pt.seed(0)
        cfg = GPTConfig(vocab_size=128, hidden_size=hidden,
                        num_layers=layers, num_heads=heads,
                        max_position_embeddings=seq, hidden_dropout=0.0,
                        attention_dropout=0.0, use_recompute=True,
                        tensor_parallel=False)
        m = GPTForCausalLM(cfg)
        opt = pt.optimizer.SGD(learning_rate=0.01,
                               parameters=m.parameters())
        step = fleet.build_train_step(m, gpt_loss_fn, opt)
        ids = pt.randint(0, 128, [batch, seq])
        temps[sched] = step.memory_stats(ids, ids).temp_size_in_bytes
    assert temps["1F1B"] < temps["F-then-B"], temps


def test_1f1b_region_memory_within_budget(restore_mesh):
    """Pipeline REGION only (what the 1F1B analytic activation budget
    describes — no embed/head/optimizer): temp bytes <= 1.2x the
    P-microbatch budget, and below the GPipe scan's region bytes
    (docs/pp_memory.md methodology; VERDICT r3 item 4 'done' bar)."""
    import jax
    import jax.numpy as jnp
    from paddle_tpu.distributed import mesh as mm
    from paddle_tpu.distributed.pipeline import (pipeline_apply_1f1b,
                                                 pipeline_apply_hybrid)
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 1, "mp_degree": 1,
                               "pp_degree": 2, "accumulate_steps": 8}
    fleet.init(is_collective=True, strategy=strategy)
    mesh = mm.get_mesh()
    P_, M, H, S, mb, lps = 2, 8, 128, 128, 2, 2

    def block(params, h, key):
        hn = h - h.mean(-1, keepdims=True)
        h = h + jax.nn.gelu(hn @ params["w1"]) @ params["w2"]
        return h, jnp.zeros((), jnp.float32)

    k0 = jax.random.PRNGKey(0)
    stacked = {"w1": 0.02 * jax.random.normal(k0, (P_, lps, H, 4 * H)),
               "w2": 0.02 * jax.random.normal(k0, (P_, lps, 4 * H, H))}
    x_mb = jax.random.normal(jax.random.fold_in(k0, 1), (M, mb, S, H))

    temps = {}
    for sched in ("F-then-B", "1F1B"):
        def loss(st, x, key):
            if sched == "1F1B":
                y, aux = pipeline_apply_1f1b(
                    jax.checkpoint(block), st, x, key, mesh,
                    n_stages=P_, n_microbatches=M)
            else:
                y, aux = pipeline_apply_hybrid(
                    jax.checkpoint(block), st, x, key, mesh,
                    n_stages=P_, n_microbatches=M, n_chunks=1)
            return jnp.sum(y * y) + aux

        g = jax.jit(jax.grad(loss))
        temps[sched] = g.lower(stacked, x_mb, k0).compile(
        ).memory_analysis().temp_size_in_bytes
    act = mb * S * H * 4
    # this block holds ~6 activation tensors per layer (hn, h@w1 x4-wide
    # counts 4, gelu, out) — use the same x12 multiplier methodology as
    # tools/pp_memory.py for a conservative budget
    f1b_budget = P_ * lps * 12 * act
    assert temps["1F1B"] <= 1.2 * f1b_budget, (temps, f1b_budget)
    assert temps["1F1B"] < temps["F-then-B"], temps
