"""Ring attention with GQA heads (round 3): grouped kv must equal full
attention with repeat_interleave'd heads — the unrepeated kv rides the
ring."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.distributed import mesh as mesh_mod
from paddle_tpu.distributed.ring_attention import ring_attention
from paddle_tpu.ops.nn_kernels import sdpa_k


@pytest.fixture
def mesh_sp4():
    prev = dict(mesh_mod._state)
    yield mesh_mod.build_mesh(dp=1, pp=1, mp=4)
    mesh_mod._state.update(prev)


def test_ring_gqa_matches_full(mesh_sp4):
    mesh = mesh_sp4
    rng = np.random.default_rng(0)
    B, L, H, Hkv, D = 2, 32, 8, 2, 16
    q = jnp.asarray(rng.standard_normal((B, L, H, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, L, Hkv, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, L, Hkv, D)), jnp.float32)
    for causal in (True, False):
        out = ring_attention(q, k, v, mesh=mesh, axis_name="mp",
                             causal=causal)
        kr = jnp.repeat(k, H // Hkv, axis=2)
        vr = jnp.repeat(v, H // Hkv, axis=2)
        ref = sdpa_k(q, kr, vr, is_causal=causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)


def test_ring_gqa_grads(mesh_sp4):
    mesh = mesh_sp4
    rng = np.random.default_rng(1)
    B, L, H, Hkv, D = 1, 16, 4, 2, 8
    q = jnp.asarray(rng.standard_normal((B, L, H, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, L, Hkv, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, L, Hkv, D)), jnp.float32)

    def loss_ring(q, k, v):
        return jnp.sum(jnp.sin(ring_attention(q, k, v, mesh=mesh,
                                              axis_name="mp")))

    def loss_ref(q, k, v):
        kr = jnp.repeat(k, H // Hkv, axis=2)
        vr = jnp.repeat(v, H // Hkv, axis=2)
        return jnp.sum(jnp.sin(sdpa_k(q, kr, vr, is_causal=True)))

    g1 = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4)


def test_ring_gqa_bad_heads_clear_error(mesh_sp4):
    q = jnp.zeros((1, 8, 8, 4), jnp.float32)
    k = jnp.zeros((1, 8, 3, 4), jnp.float32)
    with pytest.raises(ValueError, match="divisible"):
        ring_attention(q, k, k, mesh=mesh_sp4, axis_name="mp")
