"""incubate.nn.functional fused ops (reference:
python/paddle/incubate/nn/functional)."""
import numpy as np
import pytest
import jax.numpy as jnp

import paddle_tpu as pt
import paddle_tpu.nn.functional as F
from paddle_tpu.incubate.nn import functional as IF


def test_fused_rms_norm_matches_plain():
    pt.seed(0)
    x = pt.randn([2, 5, 8])
    w = pt.ones([8])
    np.testing.assert_allclose(IF.fused_rms_norm(x, w).numpy(),
                               F.rms_norm(x, w).numpy(), rtol=1e-6)


def test_fused_layer_norm_with_residual():
    pt.seed(1)
    x, r = pt.randn([2, 8]), pt.randn([2, 8])
    w, b = pt.ones([8]), pt.zeros([8])
    got = IF.fused_layer_norm(x, w, b, residual=r).numpy()
    want = F.layer_norm(x + r, [8], w, b).numpy()
    np.testing.assert_allclose(got, want, rtol=1e-5)


def test_swiglu_single_and_two_input():
    pt.seed(2)
    x = pt.randn([4, 16])
    one = IF.swiglu(x).numpy()
    a, b = x.numpy()[:, :8], x.numpy()[:, 8:]
    want = np.asarray(jnp.asarray(a) * jnp.asarray(
        1.0 / (1.0 + np.exp(-a)))) * b
    np.testing.assert_allclose(one, want, rtol=1e-4, atol=1e-5)
    two = IF.swiglu(pt.to_tensor(a), pt.to_tensor(b)).numpy()
    np.testing.assert_allclose(two, want, rtol=1e-4, atol=1e-5)
    # differentiable
    xx = pt.randn([4, 16]); xx.stop_gradient = False
    IF.swiglu(xx).mean().backward()
    assert xx.grad is not None


def test_fused_rope_matches_llama_rope():
    """interleaved style (use_neox_rotary_style=False) must equal the
    LLaMA model's own _rope."""
    from paddle_tpu.text.llama import _rope
    pt.seed(3)
    b, s, h, d = 2, 6, 4, 8
    q, k = pt.randn([b, s, h, d]), pt.randn([b, s, h, d])
    qo, ko, vo = IF.fused_rotary_position_embedding(
        q, k, use_neox_rotary_style=False)
    pos = np.arange(s)[None, :]
    wq, wk = _rope(jnp.asarray(q.numpy()), jnp.asarray(k.numpy()),
                   jnp.asarray(pos), 10000.0)
    np.testing.assert_allclose(qo.numpy(), np.asarray(wq), rtol=1e-4,
                               atol=1e-5)
    np.testing.assert_allclose(ko.numpy(), np.asarray(wk), rtol=1e-4,
                               atol=1e-5)
    assert vo is None


def test_fused_rope_neox_rotation_norm_preserving():
    pt.seed(4)
    q = pt.randn([1, 5, 2, 8])
    qo, _, _ = IF.fused_rotary_position_embedding(q)
    # rotations preserve the per-pair norm => overall vector norm
    np.testing.assert_allclose(
        np.linalg.norm(qo.numpy(), axis=-1),
        np.linalg.norm(q.numpy(), axis=-1), rtol=1e-4)


def test_fused_rope_position_ids():
    pt.seed(5)
    q = pt.randn([1, 4, 2, 8])
    ids = pt.to_tensor(np.array([[3, 4, 5, 6]], np.int32))
    qo, _, _ = IF.fused_rotary_position_embedding(
        q, position_ids=ids, use_neox_rotary_style=False)
    # matches shifting via default positions on a longer sequence
    q8_np = np.zeros((1, 8, 2, 8), np.float32)
    q8_np[:, 3:7] = q.numpy()
    qo8, _, _ = IF.fused_rotary_position_embedding(
        pt.to_tensor(q8_np), use_neox_rotary_style=False)
    np.testing.assert_allclose(qo.numpy(), qo8.numpy()[:, 3:7], rtol=1e-4,
                               atol=1e-5)


def test_fused_dropout_add_and_bias_ln():
    pt.seed(6)
    x, y = pt.randn([3, 8]), pt.randn([3, 8])
    out = IF.fused_dropout_add(x, y, p=0.0)
    np.testing.assert_allclose(out.numpy(), (x + y).numpy(), rtol=1e-6)
    w, b = pt.ones([8]), pt.zeros([8])
    got = IF.fused_bias_dropout_residual_layer_norm(
        x, y, ln_scale=w, ln_bias=b, dropout_rate=0.0).numpy()
    want = F.layer_norm(x + y, [8], w, b).numpy()
    np.testing.assert_allclose(got, want, rtol=1e-5)


def test_fused_linear():
    pt.seed(7)
    x = pt.randn([3, 4])
    w = pt.randn([4, 5])
    np.testing.assert_allclose(IF.fused_linear(x, w).numpy(),
                               x.numpy() @ w.numpy(), rtol=1e-4,
                               atol=1e-5)
    wt = pt.to_tensor(w.numpy().T.copy())
    np.testing.assert_allclose(
        IF.fused_linear(x, wt, transpose_weight=True).numpy(),
        x.numpy() @ w.numpy(), rtol=1e-4, atol=1e-5)
