"""paddle.quantization tests: QAT fake-quant + STE, PTQ observers,
int8 conversion (reference: python/paddle/quantization)."""
import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu import quantization as Q


def test_fake_quantize_values_and_ste():
    x = pt.to_tensor(np.array([-2.0, -0.5, 0.3, 1.0], np.float32))
    x.stop_gradient = False
    y = Q.fake_quantize(x, 1.0)
    # values snapped to the int8 grid of scale 1.0, clipped to [-1, 1]
    np.testing.assert_allclose(
        y.numpy(), [-1.0, -0.5039, 0.2992, 1.0], atol=2e-3)
    y.sum().backward()
    # STE: passthrough inside |x|<=scale, zero outside
    np.testing.assert_allclose(x.grad.numpy(), [0.0, 1.0, 1.0, 1.0])


def test_qat_quantize_and_train():
    pt.seed(0)
    m = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
    qat = Q.QAT()
    m = qat.quantize(m)
    assert isinstance(m[0], Q.QuantedLinear)
    opt = pt.optimizer.Adam(learning_rate=5e-3, parameters=m.parameters())
    step = pt.jit.train_step(m, lambda mm, a, b: F.mse_loss(mm(a), b), opt)
    x = pt.randn([16, 8]); y = pt.randn([16, 4])
    losses = [float(step(x, y)) for _ in range(40)]
    assert losses[-1] < losses[0] * 0.5   # trains through fake-quant
    assert float(m[0].act_q.scale) > 0    # EMA buffer updated under jit


def test_qat_convert_int8_close_to_float():
    pt.seed(1)
    m = nn.Sequential(nn.Linear(8, 8))
    x = pt.randn([4, 8])
    qat = Q.QAT()
    mq = qat.quantize(m)
    mq.train()
    mq(x)          # update scales
    mq.eval()
    ref = mq(x).numpy()
    conv = qat.convert(mq)
    assert isinstance(conv[0], Q.Int8Linear)
    out = conv(x).numpy()
    # int8 path matches the fake-quant reference closely
    assert np.abs(out - ref).max() < 0.06
    assert conv[0].w_int8.dtype == pt.int8 or \
        str(conv[0].w_int8._array.dtype) == "int8"


def test_ptq_calibrate_and_convert():
    pt.seed(2)
    m = nn.Sequential(nn.Linear(8, 8), nn.ReLU(), nn.Linear(8, 2))
    ref_in = pt.randn([32, 8])
    m.eval()
    ref = m(ref_in).numpy()
    ptq = Q.PTQ()
    mq = ptq.quantize(m)
    mq.eval()
    for i in range(4):                      # calibration passes
        mq(ref_in[i * 8:(i + 1) * 8])
    assert float(mq[0].act_q.scale) > 0
    conv = ptq.convert(mq)
    out = conv(ref_in).numpy()
    rel = np.abs(out - ref).max() / (np.abs(ref).max() + 1e-9)
    assert rel < 0.08                       # int8 PTQ error bound


def test_int8_linear_uses_int32_accumulation():
    import jax.numpy as jnp
    pt.seed(3)
    lin = nn.Linear(64, 4)
    lin.weight.set_value(pt.ones([64, 4]))
    il = Q.Int8Linear(lin, w_scale=1.0, act_scale=1.0)
    x = pt.ones([1, 64])
    out = il(x)
    # 64 * (127*127) would overflow int8/int16 paths; int32 accum is exact
    expected = 64 * (127.0 / 127.0) * (127.0 / 127.0)
    np.testing.assert_allclose(out.numpy()[0, 0] - float(lin.bias[0]),
                               expected, rtol=1e-2)


def test_inplace_false_preserves_float_model():
    pt.seed(4)
    m = nn.Sequential(nn.Linear(4, 4))
    qat = Q.QAT()
    mq = qat.quantize(m, inplace=False)
    assert isinstance(mq[0], Q.QuantedLinear)
    assert isinstance(m[0], nn.Linear)       # original untouched
    x = pt.randn([2, 4])
    m(x)  # still the float graph


def test_convert_uncalibrated_raises():
    m = nn.Sequential(nn.Linear(4, 4))
    ptq = Q.PTQ()
    mq = ptq.quantize(m)
    with pytest.raises(ValueError, match="uncalibrated"):
        ptq.convert(mq)


def test_per_type_config():
    cfg = Q.QuantConfig()
    cfg.add_type_config(nn.Conv2D, activation=Q.AbsmaxObserver)
    m = nn.Sequential(nn.Linear(4, 4), nn.Conv2D(1, 1, 3))
    mq = Q.QAT(cfg).quantize(m)
    assert isinstance(mq[0], Q.QuantedLinear)      # Linear still quantized
    assert isinstance(mq[0].act_q, Q.FakeQuanterWithAbsMax)
    assert isinstance(mq[1].act_q, Q.AbsmaxObserver)  # per-type override


def test_convert_unwraps_conv():
    pt.seed(5)
    m = nn.Sequential(nn.Conv2D(1, 2, 3, padding=1), nn.ReLU())
    ptq = Q.PTQ()
    mq = ptq.quantize(m)
    mq.eval()
    mq(pt.randn([1, 1, 8, 8]))
    conv = ptq.convert(mq)
    assert isinstance(conv[0], nn.Conv2D)    # observers gone
