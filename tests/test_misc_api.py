"""DataParallel wrapper + onnx export guidance (reference API surface)."""
import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.nn as nn


def test_data_parallel_transparent_single_process():
    pt.seed(0)
    inner = nn.Linear(4, 2)
    model = pt.DataParallel(inner)
    x = pt.randn([3, 4])
    np.testing.assert_allclose(model(x).numpy(), inner(x).numpy())
    loss = model.scale_loss((model(x) ** 2).mean())
    loss.backward()
    model.apply_collective_grads()   # no-op with one process
    assert inner.weight.grad is not None
    with model.no_sync():
        pass
    # state dict passthrough + attribute delegation
    sd = model.state_dict()
    assert "weight" in sd
    assert model.weight is inner.weight


def test_onnx_export_requires_input_spec(tmp_path):
    # export is REAL since round 4 (tests/test_onnx_export.py covers the
    # round-trips); the surface contract here: input_spec is mandatory,
    # and a valid call writes a parseable file
    m = nn.Linear(2, 2)
    with pytest.raises(ValueError, match="input_spec"):
        pt.onnx.export(m, str(tmp_path / "never"))
    p = pt.onnx.export(m, str(tmp_path / "lin"),
                       input_spec=[pt.rand([1, 2])])
    assert pt.onnx.load(p).graph.node
