"""Round-3 API-audit additions (tools/api_report.py --diff drove these;
reference: the public python/paddle/* API index — see
docs/api_coverage.md).  Numeric checks against numpy/known values."""
import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.nn.functional as F


class TestTensorOps:
    def test_aliases_and_views(self):
        x = pt.to_tensor([[1., 2.], [3., 4.]])
        np.testing.assert_allclose(pt.cat([x, x]).numpy().shape, (4, 2))
        assert pt.t(x).numpy()[0, 1] == 3.0
        assert pt.tolist(x) == [[1., 2.], [3., 4.]]
        assert float(pt.add_n([x, x, x]).sum()) == 30.0
        assert len(pt.unstack(x)) == 2
        assert float(pt.floor_mod(pt.to_tensor(7), pt.to_tensor(3))) == 1

    def test_complex_views(self):
        c = pt.as_complex(pt.to_tensor([[3., 4.]]))
        assert pt.is_complex(c)
        r = pt.as_real(c)
        np.testing.assert_allclose(r.numpy(), [[3., 4.]])

    def test_stacking(self):
        x = pt.to_tensor([[1., 2.], [3., 4.]])
        assert pt.block_diag([x, x]).shape == [4, 4]
        assert pt.hstack([x, x]).shape == [2, 4]
        assert pt.vstack([x, x]).shape == [4, 2]
        assert pt.dstack([x, x]).shape == [2, 2, 2]
        cs = pt.column_stack([pt.to_tensor([1., 2.]),
                              pt.to_tensor([3., 4.])])
        np.testing.assert_allclose(cs.numpy(), [[1., 3.], [2., 4.]])

    def test_splits(self):
        parts = pt.tensor_split(pt.arange(0, 7), 3)
        assert [int(p.shape[0]) for p in parts] == [3, 2, 2]
        assert len(pt.vsplit(pt.randn([4, 2]), 2)) == 2
        assert len(pt.hsplit(pt.randn([2, 4]), 2)) == 2

    def test_cummax_cummin(self):
        v, i = pt.cummax(pt.to_tensor([1., 3., 2., 5.]))
        np.testing.assert_allclose(v.numpy(), [1, 3, 3, 5])
        np.testing.assert_allclose(i.numpy(), [0, 1, 1, 3])
        v2, _ = pt.cummin(pt.to_tensor([3., 1., 2., 0.]))
        np.testing.assert_allclose(v2.numpy(), [3, 1, 1, 0])

    def test_indexing_ops(self):
        x = pt.to_tensor([[1., 2.], [3., 4.]])
        ip = pt.index_put(x, [pt.to_tensor([0])], pt.to_tensor([9., 9.]))
        np.testing.assert_allclose(ip.numpy(), [[9, 9], [3, 4]])
        ipa = pt.index_put(x, [pt.to_tensor([0])], pt.to_tensor([1., 1.]),
                           accumulate=True)
        np.testing.assert_allclose(ipa.numpy(), [[2, 3], [3, 4]])
        iss = pt.index_sample(x, pt.to_tensor([[1, 0], [0, 1]]))
        np.testing.assert_allclose(iss.numpy(), [[2, 1], [3, 4]])
        sn = pt.scatter_nd(pt.to_tensor([[0], [2]]),
                           pt.to_tensor([1., 2.]), [4])
        np.testing.assert_allclose(sn.numpy(), [1, 0, 2, 0])
        mx = pt.multiplex([x, x * 10], pt.to_tensor([0, 1]))
        np.testing.assert_allclose(mx.numpy(), [[1, 2], [30, 40]])

    def test_math_ops(self):
        x = pt.to_tensor([[1., 2.], [3., 4.]])
        assert float(pt.inner(pt.to_tensor([1., 2.]),
                              pt.to_tensor([3., 4.]))) == 11.0
        assert pt.kron(x, x).shape == [4, 4]
        np.testing.assert_allclose(
            pt.logit(pt.to_tensor([0.5])).numpy(), [0.0], atol=1e-6)
        assert float(pt.nanmedian(
            pt.to_tensor([1., float("nan"), 3.]))) == 2.0
        np.testing.assert_allclose(
            pt.polygamma(pt.to_tensor([1.0]), 1).numpy(),
            [np.pi ** 2 / 6], rtol=1e-4)
        assert pt.sgn(pt.to_tensor([-5.])).numpy()[0] == -1.0
        assert float(pt.dist(x, x)) == 0.0
        assert pt.stanh(pt.to_tensor([0.0])).numpy()[0] == 0.0

    def test_slicing_windows(self):
        x = pt.to_tensor([[1., 2.], [3., 4.]])
        assert pt.slice(x, [0], [0], [1]).shape == [1, 2]
        assert pt.strided_slice(pt.arange(0, 10), [0], [0], [10],
                                [2]).shape[0] == 5
        uf = pt.unfold(pt.arange(0, 6).astype("float32"), 0, 2, 2)
        np.testing.assert_allclose(uf.numpy(), [[0, 1], [2, 3], [4, 5]])
        ti = pt.tril_indices(3)
        assert ti.shape == [2, 6]
        np.testing.assert_allclose(
            pt.shard_index(pt.to_tensor([0, 1, 2, 3]), 4, 2, 0).numpy(),
            [0, 1, -1, -1])

    def test_grad_flows_through_new_ops(self):
        x = pt.to_tensor([[1., 2.], [3., 4.]], stop_gradient=False)
        loss = (pt.kron(x, x).sum() + pt.hstack([x, x]).sum()
                + pt.block_diag([x, x]).sum())
        loss.backward()
        assert x.grad is not None
        assert np.isfinite(x.grad.numpy()).all()


class TestNNAdditions:
    def test_pools_3d(self):
        vol = pt.randn([2, 3, 4, 8, 8])
        assert pt.nn.AvgPool3D(2)(vol).shape == [2, 3, 2, 4, 4]
        assert pt.nn.MaxPool3D(2)(vol).shape == [2, 3, 2, 4, 4]
        assert pt.nn.AdaptiveAvgPool3D(2)(vol).shape == [2, 3, 2, 2, 2]
        assert F.adaptive_avg_pool1d(pt.randn([2, 3, 12]), 4).shape \
            == [2, 3, 4]

    def test_pool1d_matches_2d(self):
        x = pt.randn([2, 3, 10])
        o1 = F.max_pool1d(x, 2)
        o2 = F.max_pool2d(x.unsqueeze(2), (1, 2)).squeeze(2)
        np.testing.assert_allclose(o1.numpy(), o2.numpy())

    def test_conv_transposes(self):
        out = pt.nn.Conv1DTranspose(3, 5, 3, stride=2)(pt.randn([2, 3, 10]))
        assert out.shape[:2] == [2, 5]
        vol = pt.randn([2, 3, 4, 4, 4])
        out3 = pt.nn.Conv3DTranspose(3, 5, 2, stride=2)(vol)
        assert out3.shape == [2, 5, 8, 8, 8]

    def test_conv3d_transpose_grads(self):
        vol = pt.randn([1, 2, 3, 3, 3])
        vol.stop_gradient = False
        layer = pt.nn.Conv3DTranspose(2, 2, 2)
        layer(vol).sum().backward()
        assert np.isfinite(vol.grad.numpy()).all()

    def test_norm_layers(self):
        img = pt.randn([2, 3, 8, 8])
        out = pt.nn.InstanceNorm1D(3)(pt.randn([2, 3, 10]))
        np.testing.assert_allclose(out.numpy().mean(axis=2), 0.0,
                                   atol=1e-5)
        assert F.local_response_norm(img, 5).shape == list(img.shape)

    def test_rnn_wrapper(self):
        class Cell(pt.nn.RNNCellBase):
            def __init__(self):
                super().__init__()
                self.hidden_size = 6
                self.fc = pt.nn.Linear(4 + 6, 6)

            def forward(self, x, h):
                h2 = pt.tanh(self.fc(pt.concat([x, h], axis=-1)))
                return h2, h2

        y, s = pt.nn.RNN(Cell())(pt.randn([2, 5, 4]))
        assert y.shape == [2, 5, 6] and s.shape == [2, 6]

    def test_spectral_norm_layer(self):
        pt.seed(0)
        sn = pt.nn.SpectralNorm([4, 3], power_iters=20)
        wn = sn(pt.randn([4, 3]))
        sv = np.linalg.svd(wn.numpy())[1]
        np.testing.assert_allclose(sv[0], 1.0, atol=0.05)

    def test_losses(self):
        x = pt.randn([2, 8])
        assert F.cosine_embedding_loss(x, x, pt.to_tensor([1, -1])).shape \
            == []
        assert F.margin_ranking_loss(x, x * 0.5,
                                     pt.ones([2, 8])).shape == []
        assert F.multi_margin_loss(x, pt.to_tensor([1, 2])).shape == []
        probs = F.softmax(pt.randn([2, 6, 4]))
        assert F.dice_loss(probs, pt.randint(0, 4, [2, 6, 1])).shape == []
        assert F.npair_loss(x, x, pt.to_tensor([0, 1])).shape == []
        assert F.sigmoid_focal_loss(
            x, (pt.randn([2, 8]) > 0).astype("float32")).shape == []
        assert F.triplet_margin_with_distance_loss(
            pt.randn([2, 4]), pt.randn([2, 4]), pt.randn([2, 4])).shape \
            == []
        pt.seed(0)
        hs = pt.nn.HSigmoidLoss(8, 10)
        assert hs(x, pt.to_tensor([3, 7])).shape == [2, 1]

    def test_gather_tree(self):
        ids = pt.to_tensor(np.array([[[2, 2]], [[3, 4]], [[5, 6]]],
                                    np.int32))
        parents = pt.to_tensor(np.array([[[0, 0]], [[0, 1]], [[1, 0]]],
                                        np.int32))
        out = F.gather_tree(ids, parents).numpy()
        # beam 0 final token 5 came via parent 1 (token 4) via parent 0
        np.testing.assert_array_equal(out[:, 0, 0], [2, 4, 5])

    def test_beam_search_decoder(self):
        pt.seed(1)
        emb = pt.nn.Embedding(12, 4)
        proj = pt.nn.Linear(6, 12)

        class Cell(pt.nn.RNNCellBase):
            def __init__(self):
                super().__init__()
                self.hidden_size = 6
                self.fc = pt.nn.Linear(4 + 6, 6)

            def forward(self, x, h):
                h2 = pt.tanh(self.fc(pt.concat([x, h], axis=-1)))
                return h2, h2

        bsd = pt.nn.BeamSearchDecoder(
            Cell(), start_token=1, end_token=2, beam_size=3,
            embedding_fn=emb, output_fn=proj)
        seqs, scores = bsd.decode(pt.zeros([6, 6]), batch_size=2,
                                  max_steps=4)
        assert seqs.shape == [4, 2, 3] and scores.shape == [2, 3]


class TestNamespaces:
    def test_distribution_additions(self):
        D = pt.distribution
        c = D.Cauchy(pt.to_tensor(0.0), pt.to_tensor(1.0))
        np.testing.assert_allclose(float(c.log_prob(pt.to_tensor(0.0))),
                                   -np.log(np.pi), rtol=1e-5)
        pt.seed(0)
        g = D.Geometric(pt.to_tensor(0.3))
        assert abs(float(g.sample([3000]).mean()) - 0.7 / 0.3) < 0.35
        ind = D.Independent(D.Normal(pt.zeros([3]), pt.ones([3])), 1)
        np.testing.assert_allclose(float(ind.log_prob(pt.zeros([3]))),
                                   3 * -0.5 * np.log(2 * np.pi), rtol=1e-5)

        class Exp:
            def forward(self, x):
                return x.exp()

            def inverse(self, y):
                return y.log()

            def forward_log_det_jacobian(self, x):
                return x

        td = D.TransformedDistribution(
            D.Normal(pt.to_tensor(0.0), pt.to_tensor(1.0)), [Exp()])
        np.testing.assert_allclose(float(td.log_prob(pt.to_tensor(1.0))),
                                   -0.5 * np.log(2 * np.pi), rtol=1e-5)

    def test_hub_local(self, tmp_path):
        (tmp_path / "hubconf.py").write_text(
            "def mymodel(n=3):\n"
            "    '''entrypoint doc'''\n"
            "    import paddle_tpu as pt\n"
            "    return pt.nn.Linear(n, n)\n")
        d = str(tmp_path)
        assert "mymodel" in pt.hub.list(d)
        assert "entrypoint doc" in pt.hub.help(d, "mymodel")
        m = pt.hub.load(d, "mymodel", n=4)
        assert m(pt.ones([1, 4])).shape == [1, 4]
        with pytest.raises(NotImplementedError):
            pt.hub.load("user/repo", "x", source="github")

    def test_distributed_additions(self):
        d = pt.distributed
        objs = []
        d.all_gather_object(objs, {"a": 1})
        assert objs[0]["a"] == 1
        g = d.get_group()
        assert g.nranks >= 1 and g.get_group_rank(0) == 0
        d.destroy_process_group()
        assert len(d.split(pt.ones([4, 2]), 2)) == 2

    def test_linalg_metric_lr(self):
        assert pt.linalg.matrix_norm(pt.randn([3, 3])).shape == []
        assert pt.linalg.svdvals(pt.randn([3, 4])).shape == [3]
        acc = pt.metric.accuracy(
            pt.to_tensor([[0.1, 0.9], [0.8, 0.2]]), pt.to_tensor([1, 0]))
        assert float(acc) == 1.0
        s = pt.optimizer.lr.MultiplicativeDecay(1.0, lambda e: 0.5)
        s.step(); s.step()
        np.testing.assert_allclose(s.get_lr(), 0.25)

    def test_vision_additions(self):
        from paddle_tpu.vision.models import vgg11, vgg13  # noqa: F401
        T = pt.vision.transforms
        img = (np.random.rand(16, 16, 3) * 255).astype(np.uint8)
        assert T.to_tensor(img).shape == [3, 16, 16]
        assert T.resize(img, 8).shape == (8, 8, 3)
        assert T.hflip(img).shape == img.shape
        assert T.crop(img, 2, 2, 8, 8).shape == (8, 8, 3)
        assert T.adjust_brightness(img, 1.2).shape == img.shape

    def test_vision_ops_additions(self):
        vo = pt.vision.ops
        x = pt.randn([1, 8, 16, 16])
        boxes = pt.to_tensor(np.array([[2., 2., 10., 10.]], np.float32))
        bn = pt.to_tensor(np.array([1], np.int32))
        assert vo.RoIAlign(4)(x, boxes, bn).shape == [1, 8, 4, 4]
        assert vo.RoIPool(4)(x, boxes, bn).shape == [1, 8, 4, 4]
        xp = pt.randn([1, 8, 16, 16])
        assert vo.psroi_pool(xp, boxes, bn, 2).shape == [1, 2, 2, 2]
        rois = pt.to_tensor(np.array([[0., 0., 10., 10.],
                                      [0., 0., 200., 200.]], np.float32))
        mr, restore, nums = vo.distribute_fpn_proposals(rois, 2, 5, 4, 224)
        assert len(mr) == 4
        # restore maps concatenated-by-level order back to input order
        order = np.concatenate([np.asarray(r.numpy())[:, 2] for r in mr
                                if r.shape[0]])
        yx = pt.randn([1, 3 * 7, 4, 4])
        img = pt.to_tensor(np.array([[64, 64]], np.int32))
        bx, sc = vo.yolo_box(yx, img, [10, 13, 16, 30, 33, 23], 2, 0.01,
                             16)
        assert bx.shape == [1, 48, 4] and sc.shape == [1, 48, 2]
        N, A, H, W = 1, 3, 8, 8
        props, ps, nums2 = vo.generate_proposals(
            pt.randn([N, A, H, W]), pt.randn([N, 4 * A, H, W]) * 0.1,
            pt.to_tensor(np.array([[128., 128.]], np.float32)),
            pt.randn([H, W, A, 4]).abs() * 20,
            pt.ones([H, W, A, 4]) * 0.1,
            pre_nms_top_n=50, post_nms_top_n=10)
        assert props.shape[1] == 4
        assert int(nums2.numpy()[0]) == props.shape[0]


class TestSparseNN:
    def _sample(self):
        dense = np.zeros((1, 4, 4, 4, 2), np.float32)
        dense[0, 1, 1, 1] = [1.0, -2.0]
        dense[0, 2, 3, 0] = [0.5, 4.0]
        return dense, pt.sparse.SparseCooTensor.from_dense(
            pt.to_tensor(dense))

    def test_abs_relu(self):
        _, x = self._sample()
        assert float(pt.sparse.to_dense(pt.sparse.abs(x)).min()) >= 0
        assert float(pt.sparse.to_dense(
            pt.sparse.nn.ReLU()(x)).min()) >= 0.0

    def test_batchnorm_matches_dense_masked(self):
        dense, x = self._sample()
        pt.seed(0)
        bn = pt.sparse.nn.BatchNorm(2)
        out = pt.sparse.to_dense(bn(x)).numpy()
        # per-channel stats over NON-ZERO entries only
        occ = np.abs(dense).sum(-1) > 0
        for c in range(2):
            vals = dense[..., c][occ]
            expect = (vals - vals.mean()) / np.sqrt(vals.var() + 1e-5)
            np.testing.assert_allclose(out[..., c][occ], expect,
                                       rtol=1e-4)

    def test_conv3d_matches_dense(self):
        dense, x = self._sample()
        pt.seed(0)
        conv = pt.sparse.nn.Conv3D(2, 3, 3, padding=1, bias_attr=False)
        out = pt.sparse.to_dense(conv(x)).numpy()
        # dense reference: same conv, masked to the dilated occupancy
        xt = np.moveaxis(dense, -1, 1)
        w = conv.weight.numpy().transpose(4, 3, 0, 1, 2)
        ref = pt.nn.functional.conv3d(
            pt.to_tensor(xt), pt.to_tensor(w), padding=1).numpy()
        ref = np.moveaxis(ref, 1, -1)
        occ = (np.abs(dense).sum(-1, keepdims=True) > 0).astype(np.float32)
        occ_t = np.moveaxis(occ, -1, 1)
        occ_out = pt.nn.functional.conv3d(
            pt.to_tensor(occ_t),
            pt.to_tensor(np.ones((1, 1, 3, 3, 3), np.float32)),
            padding=1).numpy()
        mask = np.moveaxis(occ_out, 1, -1) > 0
        np.testing.assert_allclose(out, ref * mask, rtol=1e-4, atol=1e-5)

    def test_subm_conv3d_pattern_and_grad(self):
        dense, x = self._sample()
        pt.seed(0)
        subm = pt.sparse.nn.SubmConv3D(2, 3, 3)
        out = pt.sparse.to_dense(subm(x))
        occ_in = np.abs(dense).sum(-1) > 0
        occ_out = np.abs(out.numpy()).sum(-1) != 0
        assert (occ_out <= occ_in).all()
        out.sum().backward()
        assert np.isfinite(subm.weight.grad.numpy()).all()
        assert float(np.abs(subm.weight.grad.numpy()).sum()) > 0


class TestNHWCResNet:
    def test_nhwc_matches_nchw(self):
        from paddle_tpu.vision.models import resnet18
        for s2d in (False, True):
            pt.seed(0)
            m1 = resnet18(num_classes=10, s2d_stem=s2d)
            pt.seed(0)
            m2 = resnet18(num_classes=10, s2d_stem=s2d,
                          data_format="NHWC")
            m2.set_state_dict(m1.state_dict())
            m1.eval(); m2.eval()
            x = np.random.RandomState(0).randn(2, 3, 32, 32).astype(
                np.float32)
            o1 = m1(pt.to_tensor(x))
            o2 = m2(pt.to_tensor(x.transpose(0, 2, 3, 1)))
            np.testing.assert_allclose(o1.numpy(), o2.numpy(), atol=1e-4,
                                       err_msg=f"s2d={s2d}")

    def test_nhwc_trains(self):
        from paddle_tpu.vision.models import resnet18
        pt.seed(0)
        m = resnet18(num_classes=4, data_format="NHWC")
        opt = pt.optimizer.Momentum(learning_rate=0.05, momentum=0.9,
                                    parameters=m.parameters())

        def loss_fn(mm, x, y):
            return F.cross_entropy(mm(x), y, reduction="mean")

        step = pt.jit.train_step(m, loss_fn, opt)
        x = pt.randn([4, 32, 32, 3])
        y = pt.randint(0, 4, [4])
        losses = [float(step(x, y)) for _ in range(6)]
        assert losses[-1] < losses[0], losses


class TestX64OptIn:
    def test_enable_x64_gives_real_float64(self):
        # VERDICT r2 weak #5: 64-bit dtypes silently degraded with no
        # opt-in path.  enable_x64 flips the policy live.
        assert pt.to_tensor([1.0], dtype="float64").dtype == pt.float32
        pt.enable_x64(True)
        try:
            t = pt.to_tensor([1.0], dtype="float64")
            assert t.dtype == pt.float64, t.dtype
            i = pt.to_tensor([1], dtype="int64")
            assert str(i.dtype) == "int64"
            # arithmetic stays 64-bit
            assert (t * 2.0).dtype == pt.float64
            assert pt.x64_enabled()
        finally:
            pt.enable_x64(False)
        assert pt.to_tensor([1.0], dtype="float64").dtype == pt.float32
        assert not pt.x64_enabled()
