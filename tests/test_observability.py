"""Unified telemetry layer: metrics registry round-trips, dispatch
counters under AMP, recompile-cause diagnosis, collective accounting,
loader instrumentation, scheduler repeat windows, and Chrome-trace export
validated by tools/trace_check.py.
"""
import importlib.util
import json
import os
import sys

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import observability as obs

_TOOLS = os.path.join(os.path.dirname(__file__), os.pardir, "tools",
                      "trace_check.py")


def _trace_check():
    spec = importlib.util.spec_from_file_location("trace_check", _TOOLS)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture
def telemetry():
    obs.reset()
    obs.enable()
    yield obs
    obs.disable()
    obs.reset()


# ===================================================================
# metrics registry
# ===================================================================
def test_registry_counter_gauge_histogram():
    reg = obs.MetricsRegistry()
    c = reg.counter("reqs_total", route="/a")
    c.inc()
    c.inc(4)
    assert c.value == 5
    assert reg.counter("reqs_total", route="/a") is c  # get-or-create
    g = reg.gauge("depth")
    g.set(7)
    g.dec(2)
    assert g.value == 5
    h = reg.histogram("lat")
    for v in range(100):
        h.observe(v / 100.0)
    assert h.count == 100
    assert 0.45 <= h.percentile(50) <= 0.55
    assert h.percentile(99) >= 0.9
    with pytest.raises(ValueError):
        reg.gauge("reqs_total", route="/a")  # kind mismatch


def test_histogram_nearest_rank_percentile():
    h = obs.metrics.Histogram()
    h.observe(1.0)
    h.observe(2.0)
    assert h.percentile(50) == 1.0   # median of two is the lower rank
    assert h.percentile(100) == 2.0
    h2 = obs.metrics.Histogram()
    for v in range(1, 101):
        h2.observe(float(v))
    assert h2.percentile(50) == 50.0
    assert h2.percentile(99) == 99.0


def test_disable_restores_default_registry():
    obs.reset()
    reg = obs.MetricsRegistry()
    obs.enable(reg)
    assert obs.registry() is reg
    pt.matmul(pt.Tensor(np.ones((2, 2), np.float32)),
              pt.Tensor(np.ones((2, 2), np.float32)))
    obs.disable()
    assert obs.registry() is not reg
    # final totals were materialized into the custom registry on disable
    names = {r["name"] for r in reg.snapshot()}
    assert "dispatch_calls_total" in names
    # a later default-registry session cannot pollute the released one
    obs.reset()
    obs.enable()
    pt.matmul(pt.Tensor(np.ones((2, 2), np.float32)),
              pt.Tensor(np.ones((2, 2), np.float32)))
    snap = [r for r in reg.snapshot()
            if r["name"] == "dispatch_calls_total"]
    assert all(r["value"] == 1 for r in snap)
    obs.disable()
    obs.reset()


def test_registry_jsonl_round_trip():
    reg = obs.MetricsRegistry()
    reg.counter("a_total", op="matmul").inc(3)
    reg.gauge("b").set(2.5)
    reg.histogram("c").observe(1.0)
    recs = [json.loads(line) for line in reg.to_jsonl().splitlines()]
    by_name = {(r["name"], tuple(sorted(r["labels"].items()))): r
               for r in recs}
    assert by_name[("a_total", (("op", "matmul"),))]["value"] == 3
    assert by_name[("b", ())]["value"] == 2.5
    assert by_name[("c", ())]["count"] == 1
    assert by_name[("c", ())]["p50"] == 1.0


def test_registry_prometheus_text():
    reg = obs.MetricsRegistry()
    reg.counter("reqs_total", route="/x").inc(2)
    reg.histogram("lat_seconds").observe(0.5)
    text = reg.to_prometheus()
    assert "# TYPE reqs_total counter" in text
    assert 'reqs_total{route="/x"} 2' in text
    assert "# TYPE lat_seconds summary" in text
    assert 'lat_seconds{quantile="0.5"} 0.5' in text
    assert "lat_seconds_count 1" in text


# ===================================================================
# dispatch-layer tracing
# ===================================================================
def test_dispatch_counters_under_amp(telemetry):
    x = pt.Tensor(np.random.randn(4, 8).astype(np.float32))
    y = pt.Tensor(np.random.randn(8, 4).astype(np.float32))
    with pt.amp.auto_cast(level="O1", dtype="bfloat16"):
        pt.matmul(x, y)
    stats = obs.dispatch_stats()
    assert stats["ops"]["matmul"] == 1
    # O1 + allow-listed matmul: both fp32 operands cast to bf16
    assert stats["amp_casts"]["matmul"] == 2
    # counters materialize into the registry at export time
    snap = {(r["name"], r["labels"].get("op")): r
            for r in obs.registry().snapshot()}
    assert snap[("dispatch_calls_total", "matmul")]["value"] == 1
    assert snap[("amp_casts_total", "matmul")]["value"] == 2


def test_dispatch_no_casts_outside_amp(telemetry):
    x = pt.Tensor(np.random.randn(4, 8).astype(np.float32))
    y = pt.Tensor(np.random.randn(8, 4).astype(np.float32))
    pt.matmul(x, y)
    assert obs.dispatch_stats()["amp_casts"] == {}


def test_pallas_override_hit_counter(telemetry):
    from paddle_tpu.ops import dispatch
    name = "_obs_test_op"
    dispatch.register(name, lambda x: x + 1)
    try:
        t = pt.Tensor(np.zeros((2,), np.float32))
        dispatch.call(name, t)
        assert obs.dispatch_stats()["pallas_hits"].get(name) is None
        dispatch.override(name, lambda x: x + 2)
        dispatch.call(name, t)
        assert obs.dispatch_stats()["pallas_hits"][name] == 1
    finally:
        dispatch._REGISTRY.pop(name, None)
        dispatch._OVERRIDDEN.discard(name)


def test_override_restore_clears_pallas_hit(telemetry):
    from paddle_tpu.ops import dispatch
    name = "_obs_restore_op"
    dispatch.register(name, lambda x: x + 1)
    try:
        t = pt.Tensor(np.zeros((2,), np.float32))
        old = dispatch.override(name, lambda x: x + 2)
        dispatch.call(name, t)
        dispatch.override(name, old)   # restore the register()-time impl
        dispatch.call(name, t)
        assert obs.dispatch_stats()["pallas_hits"][name] == 1  # not 2
    finally:
        dispatch._REGISTRY.pop(name, None)
        dispatch._OVERRIDDEN.discard(name)


def test_mesh_gauges_survive_enable_order(telemetry):
    from paddle_tpu.distributed import fleet
    fleet.init()   # before OR after enable(): collector reads live mesh
    snap = {(r["name"], r["labels"].get("axis")): r["value"]
            for r in obs.registry().snapshot()}
    assert snap[("mesh_axis_degree", "dp")] >= 1


def test_dispatch_disabled_counts_nothing():
    obs.reset()
    obs.disable()
    from paddle_tpu.ops import dispatch
    assert dispatch._TELEMETRY is None
    pt.matmul(pt.Tensor(np.ones((2, 2), np.float32)),
              pt.Tensor(np.ones((2, 2), np.float32)))
    assert obs.dispatch_stats()["ops"] == {}


# ===================================================================
# compile tracking / recompile detector
# ===================================================================
def test_recompile_detector_shape_and_dtype(telemetry):
    import paddle_tpu.jit as jit

    @jit.to_static
    def f(a):
        return a * 2 + 1

    f(pt.Tensor(np.ones((4,), np.float32)))
    f(pt.Tensor(np.ones((4,), np.float32)))   # cache hit: no new event
    f(pt.Tensor(np.ones((8,), np.float32)))
    f(pt.Tensor(np.ones((8,), np.int32)))
    causes = [e.cause for e in obs.compile_tracker.events()
              if e.label.startswith("to_static_fn(")]
    assert causes == ["first compile", "shape change", "dtype change"]
    assert all(e.wall_s >= 0 for e in obs.compile_tracker.events())


def test_recompile_detector_static_arg(telemetry):
    import paddle_tpu.jit as jit

    @jit.to_static
    def g(a, flag):
        return a + 1 if flag else a - 1

    x = pt.Tensor(np.ones((3,), np.float32))
    g(x, True)
    g(x, False)
    causes = [e.cause for e in obs.compile_tracker.events()]
    assert causes == ["first compile", "new static arg"]


def test_recompile_warning_fires(telemetry):
    import paddle_tpu.jit as jit
    obs.compile_tracker.set_warn_after(1)
    try:
        @jit.to_static
        def h(a):
            return a * 3

        h(pt.Tensor(np.ones((2,), np.float32)))
        with pytest.warns(obs.RecompileWarning, match="shape"):
            h(pt.Tensor(np.ones((5,), np.float32)))
    finally:
        obs.compile_tracker.set_warn_after(5)


def test_enable_retargets_registry_for_all_instruments():
    import paddle_tpu.jit as jit
    obs.reset()
    reg = obs.MetricsRegistry()
    obs.enable(reg)
    try:
        @jit.to_static
        def f(a):
            return a + 1

        f(pt.Tensor(np.ones((2,), np.float32)))
        names = {r["name"] for r in reg.snapshot()}
        assert "jit_compiles_total" in names       # compile tracker
        assert "dispatch_calls_total" in names     # dispatch collector
    finally:
        obs.disable()
        obs.metrics.set_registry(None)
        obs.reset()


def test_detector_tracks_instances_separately(telemetry):
    import paddle_tpu.nn as nn
    from paddle_tpu.jit import train_step

    def make_step():
        net = nn.Linear(3, 1)
        opt = pt.optimizer.SGD(learning_rate=0.1,
                               parameters=net.parameters())
        return train_step(net, lambda m, x, y: ((m(x) - y) ** 2).mean(),
                          opt)

    x = pt.Tensor(np.random.randn(4, 3).astype(np.float32))
    y = pt.Tensor(np.random.randn(4, 1).astype(np.float32))
    s1, s2 = make_step(), make_step()
    s1(x, y)
    s2(x, y)   # same label, same shapes, NEW jit cache
    evs = [e for e in obs.compile_tracker.events()
           if e.label == "TrainStep(Linear)"]
    assert [e.cause for e in evs] == ["first compile", "first compile"]
    assert obs.compile_tracker.compile_count("TrainStep(Linear)") == 2


def test_detector_prunes_on_owner_gc(telemetry):
    import gc
    from paddle_tpu.observability import compile_tracker as ct

    class Owner:
        pass

    owner = Owner()
    sig = ct.signature_of([np.ones((2,), np.float32)])
    tok = ct.on_call("prune_me", sig, owner=owner)
    ct.finish(tok)
    assert ct.compile_count("prune_me") == 1
    del owner
    gc.collect()
    # the dead owner's entry is dropped, so a recycled id can never
    # suppress a fresh instance's first compile
    assert ct.compile_count("prune_me") == 0


def test_metrics_logger_cleans_up_on_crash(tmp_path):
    import paddle_tpu.nn as nn
    from paddle_tpu.hapi import Model
    from paddle_tpu.hapi.callbacks import Callback, MetricsLogger
    obs.reset()

    class Boom(RuntimeError):
        pass

    class Exploder(Callback):
        def on_train_batch_end(self, step, logs=None):
            if step == 1:
                raise Boom()

    net = nn.Linear(2, 1)
    model = Model(net)
    model.prepare(
        pt.optimizer.SGD(learning_rate=0.1, parameters=net.parameters()),
        loss=lambda pred, label: ((pred - label) ** 2).mean())
    data = [(np.ones(2, np.float32), np.ones(1, np.float32))] * 8
    trace_path = str(tmp_path / "crash_trace.json")
    with pytest.raises(Boom):
        model.fit(data, batch_size=2, epochs=1, verbose=0,
                  callbacks=[MetricsLogger(trace_path=trace_path),
                             Exploder()])
    # telemetry released and the partial trace exported for diagnosis
    assert not obs.enabled()
    assert _trace_check().check_file(trace_path) == []
    obs.reset()


def test_detector_abort_on_failed_call(telemetry):
    import paddle_tpu.jit as jit

    @jit.to_static
    def bad(a, b):
        return pt.matmul(a, b)

    with pytest.raises(Exception):
        bad(pt.Tensor(np.ones((2, 3), np.float32)),
            pt.Tensor(np.ones((4, 5), np.float32)))   # shape mismatch
    # the failed compile neither recorded an event nor poisoned the cache
    assert obs.compile_tracker.events() == []
    a = pt.Tensor(np.ones((2, 3), np.float32))
    b = pt.Tensor(np.ones((3, 5), np.float32))
    bad(a, b)
    assert [e.cause for e in obs.compile_tracker.events()] == \
        ["first compile"]


def test_train_step_compile_event(telemetry):
    import paddle_tpu.nn as nn
    from paddle_tpu.jit import train_step
    net = nn.Linear(4, 2)
    opt = pt.optimizer.SGD(learning_rate=0.1, parameters=net.parameters())
    step = train_step(net, lambda m, x, y: ((m(x) - y) ** 2).mean(), opt)
    x = pt.Tensor(np.random.randn(8, 4).astype(np.float32))
    y = pt.Tensor(np.random.randn(8, 2).astype(np.float32))
    step(x, y)
    step(x, y)
    evs = [e for e in obs.compile_tracker.events()
           if e.label.startswith("TrainStep(")]
    assert len(evs) == 1 and evs[0].cause == "first compile"


# ===================================================================
# collective accounting
# ===================================================================
def test_collective_accounting(telemetry):
    from paddle_tpu import distributed as dist
    t = pt.Tensor(np.ones((4, 8), np.float32))
    dist.all_reduce(t)
    out = []
    dist.all_gather(out, pt.Tensor(np.ones((2, 2), np.float32)))
    snap = {(r["name"], r["labels"].get("op")): r
            for r in obs.registry().snapshot()}
    ar = snap[("comms_bytes_total", "all_reduce")]
    assert ar["value"] == 4 * 8 * 4 and ar["labels"]["axis"] == "dp"
    assert snap[("comms_calls_total", "all_reduce")]["value"] == 1
    assert snap[("comms_bytes_total", "all_gather")]["value"] == 2 * 2 * 4
    # comms spans land in the trace buffer
    cats = {e["cat"] for e in obs.trace.events()}
    assert "comms" in cats


# ===================================================================
# profiler satellites
# ===================================================================
def test_make_scheduler_repeat_windows(monkeypatch):
    from paddle_tpu import profiler as prof
    calls = {"start": 0, "stop": 0}
    monkeypatch.setattr(prof.jax.profiler, "start_trace",
                        lambda *a, **k: calls.__setitem__(
                            "start", calls["start"] + 1))
    monkeypatch.setattr(prof.jax.profiler, "stop_trace",
                        lambda: calls.__setitem__("stop", calls["stop"] + 1))
    sched = prof.make_scheduler(closed=1, record=2, repeat=3, skip_first=1)
    assert tuple(sched) == (2, 4)          # legacy first-window view
    assert sched.windows == [(2, 4), (5, 7), (8, 10)]
    p = prof.Profiler(scheduler=sched)
    p.start()
    for _ in range(12):
        p.step()
    p.stop()
    assert calls["start"] == 3 and calls["stop"] == 3
    assert p._windows_captured == 3


def test_make_scheduler_single_window_back_compat(monkeypatch):
    from paddle_tpu import profiler as prof
    sched = prof.make_scheduler(skip_first=1, record=2)
    assert tuple(sched) == (1, 3)
    assert sched.windows == [(1, 3)]


def test_profiler_summary_sorted_by():
    from paddle_tpu import profiler as prof
    prof.reset_events()
    # many fast "a" events, one slow "b" event
    prof._event_stats["a"] = [10, 0.010, 0.002]
    prof._event_stats["b"] = [1, 0.100, 0.100]
    by_total = prof.Profiler(timer_only=True).summary(sorted_by="total")
    by_count = prof.Profiler(timer_only=True).summary(sorted_by="count")
    lines_t = [ln for ln in by_total.splitlines() if ln[:1] in "ab"]
    lines_c = [ln for ln in by_count.splitlines() if ln[:1] in "ab"]
    assert lines_t[0].startswith("b") and lines_c[0].startswith("a")
    avg = prof.Profiler(timer_only=True).summary(sorted_by="avg")
    mx = prof.Profiler(timer_only=True).summary(sorted_by="max")
    assert [ln for ln in avg.splitlines() if ln[:1] in "ab"][0][0] == "b"
    assert [ln for ln in mx.splitlines() if ln[:1] in "ab"][0][0] == "b"
    with pytest.raises(ValueError):
        prof.Profiler(timer_only=True).summary(sorted_by="bogus")
    prof.reset_events()


# ===================================================================
# Model.fit + MetricsLogger → Chrome trace (acceptance path)
# ===================================================================
def test_metrics_logger_fit_chrome_trace(tmp_path):
    import paddle_tpu.nn as nn
    from paddle_tpu.hapi import Model
    from paddle_tpu.hapi.callbacks import Callback, MetricsLogger
    obs.reset()
    assert not obs.enabled()

    xs = np.random.randn(16, 4).astype(np.float32)
    ys = np.random.randn(16, 2).astype(np.float32)
    data = list(zip(xs, ys))
    net = nn.Linear(4, 2)
    model = Model(net)
    model.prepare(
        pt.optimizer.SGD(learning_rate=0.1, parameters=net.parameters()),
        loss=lambda pred, label: ((pred - label) ** 2).mean())
    class EpochMarker(Callback):
        """RecordEvent spans from inside the run merge into its trace."""

        def on_epoch_end(self, epoch, logs=None):
            with pt.profiler.RecordEvent("epoch_mark"):
                pass

    trace_path = str(tmp_path / "fit_trace.json")
    logger = MetricsLogger(trace_path=trace_path, batch_size=4)
    obs.enable()
    history = model.fit(data, batch_size=4, epochs=2, verbose=0,
                        callbacks=[logger, EpochMarker()])
    # telemetry was already on, so MetricsLogger must NOT disable it
    assert obs.enabled()
    obs.disable()
    # percentiles + throughput + memory gauge in the epoch logs
    assert "step_time_p50" in history[0]
    assert "steps_per_s" in history[0]
    assert history[0]["samples_per_s"] > 0
    assert history[0]["live_array_bytes"] > 0
    # the trace file is schema-valid and holds step+compile+RecordEvent
    tc = _trace_check()
    assert tc.check_file(trace_path,
                         require_cats=("step", "compile", "host")) == []
    events = json.load(open(trace_path))["traceEvents"]
    names = {e["name"] for e in events}
    assert "train_step" in names
    assert any(n.startswith("compile:TrainStep(") for n in names)
    assert "epoch_mark" in names         # RecordEvent span merged in
    # registry saw the steps: 2 epochs x 4 batches
    reg = obs.registry()
    assert reg.counter("fit_steps_total").value == 8
    assert reg.histogram("fit_step_seconds").count == 8
    obs.reset()


def test_metrics_logger_owns_telemetry_when_off():
    import paddle_tpu.nn as nn
    from paddle_tpu.hapi import Model
    from paddle_tpu.hapi.callbacks import MetricsLogger
    obs.reset()
    assert not obs.enabled()
    net = nn.Linear(2, 1)
    model = Model(net)
    model.prepare(
        pt.optimizer.SGD(learning_rate=0.1, parameters=net.parameters()),
        loss=lambda pred, label: ((pred - label) ** 2).mean())
    data = [(np.ones(2, np.float32), np.ones(1, np.float32))] * 4
    model.fit(data, batch_size=2, epochs=1, verbose=0,
              callbacks=[MetricsLogger()])
    assert not obs.enabled()   # enabled for the fit, released after
    assert obs.registry().counter("fit_steps_total").value == 2
    obs.reset()


def test_trace_check_cli_and_rejects_invalid(tmp_path):
    tc = _trace_check()
    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps({"traceEvents": [
        {"name": "x", "ph": "X", "ts": -5, "dur": 1},     # bad ts
        {"name": "y", "ph": "??", "ts": 0},               # bad phase
        {"ph": "X", "ts": 0, "dur": -1},                  # no name, bad dur
        {"name": "z", "ph": "X", "ts": 0, "dur": 2, "pid": "p"},
    ]}))
    errs = tc.check_file(str(bad))
    assert len(errs) >= 4
    assert tc.main(["trace_check", str(bad)]) == 1
    good = tmp_path / "good.json"
    good.write_text(json.dumps({"traceEvents": [
        {"name": "a", "ph": "X", "ts": 0.0, "dur": 1.0, "pid": 1,
         "tid": 1, "cat": "step"}]}))
    assert tc.check_file(str(good)) == []
    assert tc.main(["trace_check", str(good)]) == 0
    assert tc.main(["trace_check", str(good),
                    "--require-cats=step"]) == 0
    assert tc.main(["trace_check", str(good),
                    "--require-cats=compile"]) == 1
    # space-separated form from the usage line works too
    assert tc.main(["trace_check", str(good),
                    "--require-cats", "step"]) == 0


def test_second_fit_trace_excludes_first_run(tmp_path):
    import paddle_tpu.nn as nn
    from paddle_tpu.hapi import Model
    from paddle_tpu.hapi.callbacks import MetricsLogger
    obs.reset()
    net = nn.Linear(2, 1)
    model = Model(net)
    model.prepare(
        pt.optimizer.SGD(learning_rate=0.1, parameters=net.parameters()),
        loss=lambda pred, label: ((pred - label) ** 2).mean())
    data = [(np.ones(2, np.float32), np.ones(1, np.float32))] * 4
    p1, p2 = str(tmp_path / "run1.json"), str(tmp_path / "run2.json")
    model.fit(data, batch_size=2, epochs=1, verbose=0,
              callbacks=[MetricsLogger(trace_path=p1)])
    model.fit(data, batch_size=2, epochs=1, verbose=0,
              callbacks=[MetricsLogger(trace_path=p2)])
    n1 = sum(1 for e in json.load(open(p1))["traceEvents"]
             if e["name"] == "train_step")
    n2 = sum(1 for e in json.load(open(p2))["traceEvents"]
             if e["name"] == "train_step")
    assert n1 == 2 and n2 == 2   # run 2 does NOT replay run 1's spans
    obs.reset()


def test_span_contextmanager(telemetry):
    with obs.span("unit_of_work", cat="host", args={"k": 1}):
        pass
    evs = [e for e in obs.trace.events() if e["name"] == "unit_of_work"]
    assert len(evs) == 1 and evs[0]["ph"] == "X" and evs[0]["dur"] >= 0


# ===================================================================
# loader instrumentation
# ===================================================================
def test_shm_loader_metrics(telemetry):
    from paddle_tpu.io import native, DataLoader, Dataset
    if not native.available():
        pytest.skip("native ring unavailable")

    class Ds(Dataset):
        def __len__(self):
            return 12

        def __getitem__(self, i):
            return np.full((3,), i, np.float32)

    n = sum(1 for _ in DataLoader(Ds(), batch_size=4, num_workers=2))
    assert n == 3
    reg = obs.registry()
    assert reg.histogram("loader_batch_wait_seconds").count == 3
    snap = {r["name"] for r in reg.snapshot()}
    assert "loader_queue_depth" in snap
