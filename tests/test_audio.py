"""paddle.audio tests (windows, mel scale, feature layers)."""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.audio import functional as AF
from paddle_tpu.audio.features import (Spectrogram, MelSpectrogram,
                                       LogMelSpectrogram, MFCC)


def test_windows_match_numpy():
    np.testing.assert_allclose(
        AF.get_window("hann", 16, fftbins=False).numpy(),
        np.hanning(16), atol=1e-6)
    np.testing.assert_allclose(
        AF.get_window("hamming", 16, fftbins=False).numpy(),
        np.hamming(16), atol=1e-6)
    np.testing.assert_allclose(
        AF.get_window("blackman", 16, fftbins=False).numpy(),
        np.blackman(16), atol=1e-6)


def test_mel_scale_roundtrip():
    for htk in (False, True):
        for hz in (60.0, 440.0, 4000.0):
            mel = AF.hz_to_mel(hz, htk=htk)
            np.testing.assert_allclose(AF.mel_to_hz(mel, htk=htk), hz,
                                       rtol=1e-6)


def test_fbank_shape_and_partition():
    fb = AF.compute_fbank_matrix(16000, 512, n_mels=40).numpy()
    assert fb.shape == (40, 257)
    assert (fb >= 0).all()
    # every filter has some support
    assert (fb.sum(1) > 0).all()


def test_spectrogram_shapes_and_parseval():
    sr = 16000
    t = np.linspace(0, 1, sr, endpoint=False)
    x = np.sin(2 * np.pi * 440 * t).astype(np.float32)
    spec = Spectrogram(n_fft=512, hop_length=160)(pt.to_tensor(x[None]))
    assert tuple(spec.shape) == (1, 257, sr // 160 + 1)
    # peak frequency bin ~ 440 Hz
    avg = spec.numpy()[0].mean(-1)
    peak_hz = np.argmax(avg) * sr / 512
    assert abs(peak_hz - 440) < 40


def test_mel_and_logmel_and_mfcc_shapes():
    x = pt.randn([2, 8000])
    mel = MelSpectrogram(sr=16000, n_fft=512, n_mels=40)(x)
    assert tuple(mel.shape)[:2] == (2, 40)
    logmel = LogMelSpectrogram(sr=16000, n_fft=512, n_mels=40)(x)
    assert tuple(logmel.shape) == tuple(mel.shape)
    mfcc = MFCC(sr=16000, n_mfcc=13, n_fft=512, n_mels=40)(x)
    assert tuple(mfcc.shape)[:2] == (2, 13)


def test_power_to_db_flooring():
    x = pt.to_tensor(np.array([1.0, 0.1, 1e-12], np.float32))
    db = AF.power_to_db(x, top_db=30.0).numpy()
    np.testing.assert_allclose(db[0], 0.0, atol=1e-5)
    np.testing.assert_allclose(db[1], -10.0, atol=1e-4)
    assert db[2] == pytest.approx(-30.0)   # floored by top_db


def test_mfcc_backprops_to_waveform():
    x = pt.randn([1, 4096]); x.stop_gradient = False
    out = MFCC(sr=16000, n_mfcc=8, n_fft=256, n_mels=24)(x)
    out.sum().backward()
    assert x.grad is not None
    assert np.isfinite(x.grad.numpy()).all()


def test_top_level_summary_works():
    import paddle_tpu.nn as nn
    info = pt.summary(nn.Linear(3, 4))
    assert info["total_params"] == 16


def test_profiler_step_after_stop_is_inert():
    from paddle_tpu import profiler as prof
    p = prof.Profiler(timer_only=True)
    p.start(); p.step(); p.stop()
    p.step()   # must not restart anything
    assert "steps=1" in p.summary()


# ------------------------------------------------- round-5 parity pins
# (VERDICT r4 item 9: real numerics parity, independently pinned against
# scipy and torch — both ship in this environment)

def test_windows_match_scipy_catalogue():
    import scipy.signal as sps
    for win in ("hann", "hamming", "blackman", "bartlett", "bohman",
                "nuttall", "blackmanharris", "cosine", "triang",
                ("kaiser", 8.6), ("tukey", 0.5), ("gaussian", 7),
                ("exponential", None, 1.0), "taylor", "boxcar"):
        for fftbins in (True, False):
            got = AF.get_window(win, 32, fftbins=fftbins).numpy()
            want = sps.get_window(win, 32, fftbins=fftbins)
            np.testing.assert_allclose(got, want, atol=1e-6,
                                       err_msg=str(win))


def test_spectrogram_matches_torch_stft():
    import torch
    rng = np.random.RandomState(0)
    x = rng.randn(2, 4000).astype(np.float32)
    n_fft, hop = 512, 160
    spec = Spectrogram(n_fft=n_fft, hop_length=hop,
                       power=2.0)(pt.to_tensor(x)).numpy()
    tw = torch.hann_window(n_fft, periodic=True)
    tspec = torch.stft(torch.from_numpy(x), n_fft, hop_length=hop,
                       window=tw, center=True, pad_mode="reflect",
                       return_complex=True)
    want = (tspec.abs() ** 2).numpy()
    np.testing.assert_allclose(spec, want, rtol=1e-3, atol=1e-3)


def test_mfcc_matches_scipy_dct_composition():
    """MFCC == scipy.fft.dct(type 2, ortho) applied over the log-mel
    bands — pins the DCT matrix + the layer's transpose plumbing."""
    from scipy.fft import dct as sp_dct
    rng = np.random.RandomState(1)
    x = rng.randn(1, 8000).astype(np.float32)
    kw = dict(sr=16000, n_fft=512, n_mels=40)
    logmel = LogMelSpectrogram(**kw)(pt.to_tensor(x)).numpy()
    got = MFCC(n_mfcc=13, **kw)(pt.to_tensor(x)).numpy()
    want = sp_dct(logmel[0].T, type=2, norm="ortho",
                  axis=-1)[:, :13].T[None]
    np.testing.assert_allclose(got, want, rtol=1e-3, atol=1e-3)


def test_mel_frequencies_and_fft_frequencies():
    ff = AF.fft_frequencies(16000, 512).numpy()
    assert ff.shape == (257,) and ff[0] == 0 and abs(ff[-1] - 8000) < 1e-3
    mf = AF.mel_frequencies(40, 50.0, 8000.0).numpy()
    assert mf.shape == (40,)
    assert abs(mf[0] - 50.0) < 1e-2 and abs(mf[-1] - 8000.0) < 1.0
    assert (np.diff(mf) > 0).all()        # strictly increasing


def test_feature_grads_reach_waveform():
    x = pt.randn([1, 2048])
    x.stop_gradient = False
    out = LogMelSpectrogram(sr=16000, n_fft=256, n_mels=20)(x)
    out.sum().backward()
    g = x.grad.numpy()
    assert np.isfinite(g).all() and np.abs(g).sum() > 0


def test_features_under_jit_train_step():
    """An audio classifier head trains through MelSpectrogram in the
    fused step (feature layers are jit-clean)."""
    import paddle_tpu.nn.functional as F

    class Clf(pt.nn.Layer):
        def __init__(self):
            super().__init__()
            self.mel = MelSpectrogram(sr=16000, n_fft=256, n_mels=20,
                                      f_min=0.0)
            self.fc = pt.nn.Linear(20, 2)

        def forward(self, x):
            m = self.mel(x)               # [B, mel, T]
            return self.fc(m.mean(axis=2))

    pt.seed(0)
    model = Clf()
    opt = pt.optimizer.Adam(learning_rate=2e-2,
                            parameters=model.parameters())
    step = pt.jit.train_step(
        model, lambda m, x, y: F.cross_entropy(m(x), y), opt)
    rng = np.random.RandomState(0)
    t = np.arange(4096) / 16000.0
    losses = []
    for i in range(25):
        y = i % 2
        hz = 500.0 if y == 0 else 3000.0
        sig = np.sin(2 * np.pi * hz * t) + 0.1 * rng.randn(4096)
        losses.append(float(step(
            pt.to_tensor(sig.astype(np.float32)[None]),
            pt.to_tensor(np.array([y])))))
    assert np.mean(losses[-5:]) < np.mean(losses[:5]), losses


class TestWavBackend:
    def _sine(self, C=2, T=1600):
        t = np.arange(T) / 16000.0
        x = np.stack([np.sin(2 * np.pi * 440 * t),
                      0.5 * np.cos(2 * np.pi * 220 * t)][:C])
        return x.astype(np.float32)          # [C, T]

    @pytest.mark.parametrize("bits", [8, 16, 24, 32])
    def test_pcm_roundtrip(self, tmp_path, bits):
        from paddle_tpu import audio
        x = self._sine()
        p = str(tmp_path / f"t{bits}.wav")
        audio.save(p, x, 16000, encoding="PCM_S", bits_per_sample=bits)
        meta = audio.info(p)
        assert (meta.sample_rate, meta.num_channels,
                meta.bits_per_sample, meta.num_frames) == (16000, 2,
                                                           bits, 1600)
        y, sr = audio.load(p)
        assert sr == 16000 and tuple(y.shape) == (2, 1600)
        tol = 1.0 / (2 ** (bits - 1)) + 1e-6
        np.testing.assert_allclose(y.numpy(), x, atol=tol)

    def test_float_roundtrip_exact(self, tmp_path):
        from paddle_tpu import audio
        x = self._sine()
        p = str(tmp_path / "f32.wav")
        audio.save(p, x, 22050, encoding="PCM_F")
        y, sr = audio.load(p)
        assert sr == 22050
        np.testing.assert_array_equal(y.numpy(), x)   # bit-exact

    def test_offset_frames_channels_last(self, tmp_path):
        from paddle_tpu import audio
        x = self._sine()
        p = str(tmp_path / "o.wav")
        audio.save(p, x, 16000)
        y, _ = audio.load(p, frame_offset=100, num_frames=50,
                          channels_first=False)
        assert tuple(y.shape) == (50, 2)
        np.testing.assert_allclose(y.numpy(), x.T[100:150], atol=1e-4)

    def test_unnormalized_ints(self, tmp_path):
        from paddle_tpu import audio
        x = self._sine()
        p = str(tmp_path / "i.wav")
        audio.save(p, x, 16000, bits_per_sample=16)
        y, _ = audio.load(p, normalize=False)
        assert y.numpy().dtype == np.int16
        assert np.abs(y.numpy()).max() > 10000   # near full-scale ints

    def test_stdlib_wave_interop(self, tmp_path):
        """Our writer's files parse with the stdlib wave module and
        vice versa (independent codec pin)."""
        import wave as stdwave
        from paddle_tpu import audio
        x = self._sine(C=1)
        p = str(tmp_path / "w.wav")
        audio.save(p, x, 8000, bits_per_sample=16)
        with stdwave.open(p) as w:
            assert (w.getframerate(), w.getnchannels(),
                    w.getsampwidth(), w.getnframes()) == (8000, 1, 2,
                                                          1600)
            raw = np.frombuffer(w.readframes(1600), np.int16)
        np.testing.assert_allclose(raw / 32768.0, x[0], atol=1e-4)
        # stdlib-written file loads back through our parser
        p2 = str(tmp_path / "w2.wav")
        with stdwave.open(p2, "wb") as w:
            w.setnchannels(1)
            w.setsampwidth(2)
            w.setframerate(8000)
            w.writeframes(raw.tobytes())
        y, sr = audio.load(p2)
        assert sr == 8000
        np.testing.assert_allclose(y.numpy()[0], x[0], atol=1e-4)

    def test_backend_registry(self):
        from paddle_tpu.audio import backends as B
        assert B.list_available_backends() == ["wave_backend"]
        assert B.get_current_backend() == "wave_backend"
        with pytest.raises(NotImplementedError):
            B.set_backend("soundfile")


def test_window_fallback_matches_scipy_path(monkeypatch):
    """The no-scipy hand-rolled windows must track the scipy results so
    a scipy-less deployment gets the same numerics for the core set."""
    import sys
    want = {name: AF.get_window(name, 24, fftbins=True).numpy()
            for name in ("hann", "hamming", "blackman", "bartlett",
                         "bohman", "boxcar")}
    monkeypatch.setitem(sys.modules, "scipy.signal", None)
    for name, ref in want.items():
        got = AF.get_window(name, 24, fftbins=True).numpy()
        np.testing.assert_allclose(got, ref, atol=1e-6, err_msg=name)


def test_save_rescales_wide_integer_input(tmp_path):
    """int32 samples saved at the default 16-bit must re-quantize, not
    wrap modulo 2^16."""
    from paddle_tpu import audio
    t = np.arange(400) / 16000.0
    x = np.sin(2 * np.pi * 440 * t).astype(np.float32)[None]
    p1 = str(tmp_path / "a.wav")
    audio.save(p1, x, 16000, bits_per_sample=32)
    y32, _ = audio.load(p1, normalize=False)      # int32 near full scale
    p2 = str(tmp_path / "b.wav")
    audio.save(p2, y32, 16000, bits_per_sample=16)
    y, _ = audio.load(p2)                          # normalized float
    np.testing.assert_allclose(y.numpy(), x, atol=2e-4)


def test_odd_payload_gets_riff_pad(tmp_path):
    from paddle_tpu import audio
    x = (np.sin(np.arange(101) / 5.0)).astype(np.float32)[None]
    p = str(tmp_path / "odd.wav")
    audio.save(p, x, 8000, bits_per_sample=8)      # 101-byte payload
    import os as _os
    size = _os.path.getsize(p)
    assert size % 2 == 0                           # pad byte written
    y, sr = audio.load(p)
    assert tuple(y.shape) == (1, 101) and sr == 8000


def test_unnormalized_roundtrip_is_lossless(tmp_path):
    """load(normalize=False) -> save must round-trip bit-exactly for
    every PCM width (the int container's dtype encodes the sample
    width, so re-saving re-quantizes at the right full scale)."""
    from paddle_tpu import audio
    t = np.arange(320) / 16000.0
    x = (0.8 * np.sin(2 * np.pi * 440 * t)).astype(np.float32)[None]
    for bits in (8, 16, 24, 32):
        p1 = str(tmp_path / f"r{bits}.wav")
        audio.save(p1, x, 16000, bits_per_sample=bits)
        y1, _ = audio.load(p1, normalize=False)
        p2 = str(tmp_path / f"r{bits}b.wav")
        audio.save(p2, y1, 16000, bits_per_sample=bits)
        y2, _ = audio.load(p2, normalize=False)
        np.testing.assert_array_equal(y1.numpy(), y2.numpy())
        z, _ = audio.load(p2)      # and it still decodes near x
        np.testing.assert_allclose(z.numpy(), x,
                                   atol=1.0 / 2 ** (bits - 1) + 2e-3)
