"""paddle.audio tests (windows, mel scale, feature layers)."""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.audio import functional as AF
from paddle_tpu.audio.features import (Spectrogram, MelSpectrogram,
                                       LogMelSpectrogram, MFCC)


def test_windows_match_numpy():
    np.testing.assert_allclose(
        AF.get_window("hann", 16, fftbins=False).numpy(),
        np.hanning(16), atol=1e-6)
    np.testing.assert_allclose(
        AF.get_window("hamming", 16, fftbins=False).numpy(),
        np.hamming(16), atol=1e-6)
    np.testing.assert_allclose(
        AF.get_window("blackman", 16, fftbins=False).numpy(),
        np.blackman(16), atol=1e-6)


def test_mel_scale_roundtrip():
    for htk in (False, True):
        for hz in (60.0, 440.0, 4000.0):
            mel = AF.hz_to_mel(hz, htk=htk)
            np.testing.assert_allclose(AF.mel_to_hz(mel, htk=htk), hz,
                                       rtol=1e-6)


def test_fbank_shape_and_partition():
    fb = AF.compute_fbank_matrix(16000, 512, n_mels=40).numpy()
    assert fb.shape == (40, 257)
    assert (fb >= 0).all()
    # every filter has some support
    assert (fb.sum(1) > 0).all()


def test_spectrogram_shapes_and_parseval():
    sr = 16000
    t = np.linspace(0, 1, sr, endpoint=False)
    x = np.sin(2 * np.pi * 440 * t).astype(np.float32)
    spec = Spectrogram(n_fft=512, hop_length=160)(pt.to_tensor(x[None]))
    assert tuple(spec.shape) == (1, 257, sr // 160 + 1)
    # peak frequency bin ~ 440 Hz
    avg = spec.numpy()[0].mean(-1)
    peak_hz = np.argmax(avg) * sr / 512
    assert abs(peak_hz - 440) < 40


def test_mel_and_logmel_and_mfcc_shapes():
    x = pt.randn([2, 8000])
    mel = MelSpectrogram(sr=16000, n_fft=512, n_mels=40)(x)
    assert tuple(mel.shape)[:2] == (2, 40)
    logmel = LogMelSpectrogram(sr=16000, n_fft=512, n_mels=40)(x)
    assert tuple(logmel.shape) == tuple(mel.shape)
    mfcc = MFCC(sr=16000, n_mfcc=13, n_fft=512, n_mels=40)(x)
    assert tuple(mfcc.shape)[:2] == (2, 13)


def test_power_to_db_flooring():
    x = pt.to_tensor(np.array([1.0, 0.1, 1e-12], np.float32))
    db = AF.power_to_db(x, top_db=30.0).numpy()
    np.testing.assert_allclose(db[0], 0.0, atol=1e-5)
    np.testing.assert_allclose(db[1], -10.0, atol=1e-4)
    assert db[2] == pytest.approx(-30.0)   # floored by top_db


def test_mfcc_backprops_to_waveform():
    x = pt.randn([1, 4096]); x.stop_gradient = False
    out = MFCC(sr=16000, n_mfcc=8, n_fft=256, n_mels=24)(x)
    out.sum().backward()
    assert x.grad is not None
    assert np.isfinite(x.grad.numpy()).all()


def test_top_level_summary_works():
    import paddle_tpu.nn as nn
    info = pt.summary(nn.Linear(3, 4))
    assert info["total_params"] == 16


def test_profiler_step_after_stop_is_inert():
    from paddle_tpu import profiler as prof
    p = prof.Profiler(timer_only=True)
    p.start(); p.step(); p.stop()
    p.step()   # must not restart anything
    assert "steps=1" in p.summary()
