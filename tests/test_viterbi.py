"""paddle.text.viterbi_decode vs brute force (reference:
python/paddle/text/viterbi_decode.py)."""
import itertools

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.text import ViterbiDecoder, viterbi_decode


def _brute(pot_b, trans, L, N, bos_eos):
    best, best_path = -1e30, None
    for path in itertools.product(range(N), repeat=L):
        s = pot_b[0][path[0]] + (trans[N - 2][path[0]] if bos_eos else 0.0)
        for t in range(1, L):
            s += trans[path[t - 1]][path[t]] + pot_b[t][path[t]]
        if bos_eos:
            s += trans[path[L - 1]][N - 1]
        if s > best:
            best, best_path = s, path
    return best, best_path


@pytest.mark.parametrize("bos_eos", [True, False])
def test_viterbi_matches_brute_force(bos_eos):
    rng = np.random.RandomState(0)
    B, T, N = 3, 6, 5
    pot = rng.randn(B, T, N).astype(np.float32)
    trans = rng.randn(N, N).astype(np.float32)
    lens = np.array([6, 4, 1], np.int32)
    scores, paths = viterbi_decode(pt.to_tensor(pot), pt.to_tensor(trans),
                                   pt.to_tensor(lens),
                                   include_bos_eos_tag=bos_eos)
    for b in range(B):
        L = int(lens[b])
        want_s, want_p = _brute(pot[b], trans, L, N, bos_eos)
        np.testing.assert_allclose(float(scores.numpy()[b]), want_s,
                                   rtol=1e-4)
        assert tuple(paths.numpy()[b][:L]) == want_p


def test_viterbi_decoder_class_and_jit():
    import jax
    from paddle_tpu.ops.dispatch import call_raw
    rng = np.random.RandomState(1)
    pot = rng.randn(2, 4, 4).astype(np.float32)
    trans = rng.randn(4, 4).astype(np.float32)
    lens = np.array([4, 4], np.int32)
    dec = ViterbiDecoder(pt.to_tensor(trans), include_bos_eos_tag=False)
    s, p = dec(pt.to_tensor(pot), pt.to_tensor(lens))
    assert p.shape == [2, 4]
    # the whole decode compiles as one XLA program
    s2, p2 = jax.jit(lambda a, t, l: call_raw(
        "viterbi_decode", a, t, l, include_bos_eos_tag=False))(
            pot, trans, lens)
    np.testing.assert_allclose(np.asarray(s2), s.numpy(), rtol=1e-5)
    np.testing.assert_array_equal(np.asarray(p2), p.numpy())
