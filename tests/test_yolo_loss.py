"""paddle.vision.ops.yolo_loss — YOLOv3 training loss.

Semantic checks (the reference kernel is CPU/CUDA loops; ours is masked
vector math, vision/ops.py _yolo_loss_impl): a head constructed to
predict a gt box exactly should incur ~zero positive-sample loss; the
loss must be differentiable w.r.t. x; ignored (high-IoU) cells must not
pay noobj loss; and a tiny head must overfit one target.
"""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.vision import ops as vops

ANCHORS = [10, 13, 16, 30, 33, 23]     # one 3-anchor level
MASK = [0, 1, 2]
CLS = 4
H = W = 8
DOWN = 32                               # input 256x256


def _head(seed=0, scale=0.01):
    rng = np.random.RandomState(seed)
    return rng.randn(2, len(MASK) * (5 + CLS), H, W).astype(np.float32) * scale


def _gt(cx, cy, w, h, label, batch=2, pad_to=3):
    gt_box = np.zeros((batch, pad_to, 4), np.float32)
    gt_label = np.zeros((batch, pad_to), np.int64)
    gt_box[:, 0] = [cx, cy, w, h]
    gt_label[:, 0] = label
    return gt_box, gt_label


def _loss(x, gt_box, gt_label, **kw):
    t = pt.to_tensor(x)
    t.stop_gradient = False
    out = vops.yolo_loss(t, pt.to_tensor(gt_box), pt.to_tensor(gt_label),
                         anchors=ANCHORS, anchor_mask=MASK, class_num=CLS,
                         ignore_thresh=0.7, downsample_ratio=DOWN, **kw)
    return t, out


class TestYoloLoss:
    def test_shape_and_grad_flow(self):
        gt_box, gt_label = _gt(0.5, 0.5, 0.2, 0.3, 2)
        t, loss = _loss(_head(), gt_box, gt_label)
        assert loss.shape == [2]
        loss.sum().backward()
        g = t.grad.numpy()
        assert list(g.shape) == list(t.shape) and np.isfinite(g).all()
        assert np.abs(g).max() > 0

    def test_perfect_prediction_near_zero_positive_loss(self):
        # gt of exactly anchor-1's shape centered in cell (4,4); build x so
        # the responsible cell predicts it exactly and all sigmoids saturate
        aw, ah = ANCHORS[2], ANCHORS[3]          # anchor index 1 of mask
        gw, gh = aw / (W * DOWN), ah / (H * DOWN)
        gt_box, gt_label = _gt(4.5 / W, 4.5 / H, gw, gh, 1)
        x = np.zeros((2, len(MASK) * (5 + CLS), H, W), np.float32)
        x[:, :, :, :] = -12.0                    # sigmoid ~ 0 everywhere
        base = 1 * (5 + CLS)                     # anchor slot 1
        x[:, base + 0, 4, 4] = 0.0               # sigmoid 0.5 = offset .5
        x[:, base + 1, 4, 4] = 0.0
        x[:, base + 2, 4, 4] = 0.0               # tw = log(gw*in/aw) = 0
        x[:, base + 3, 4, 4] = 0.0
        x[:, base + 4, 4, 4] = 12.0              # objectness ~ 1
        x[:, base + 5 + 1, 4, 4] = 12.0          # class 1 ~ 1
        _, loss = _loss(x, gt_box, gt_label, use_label_smooth=False)
        v = loss.numpy()
        # x/y use BCE against the 0.5-offset target, whose minimum is the
        # target's entropy (2*H(0.5) = 2*ln2), scaled by (2 - gw*gh); all
        # other components must be ~0 at a perfect prediction
        floor = 2.0 * np.log(2.0) * (2.0 - gw * gh)
        assert (np.abs(v - floor) < 0.05).all(), (v, floor)

    def test_wrong_prediction_losses_more(self):
        gt_box, gt_label = _gt(0.55, 0.55, 0.15, 0.2, 3)
        _, l_small = _loss(_head(0, 0.01), gt_box, gt_label)
        _, l_big = _loss(_head(0, 3.0), gt_box, gt_label)
        assert l_big.numpy().sum() > l_small.numpy().sum()

    def test_no_valid_gt_means_only_noobj(self):
        # all-zero gt boxes are padding: loss is pure noobj objectness
        gt_box = np.zeros((2, 3, 4), np.float32)
        gt_label = np.zeros((2, 3), np.int64)
        x = np.full((2, len(MASK) * (5 + CLS), H, W), -12.0, np.float32)
        _, loss = _loss(x, gt_box, gt_label)
        assert (loss.numpy() < 0.01).all()

    def test_gt_score_scales_positive_loss(self):
        gt_box, gt_label = _gt(0.5, 0.5, 0.2, 0.3, 2)
        x = _head(1, 0.5)
        _, l_full = _loss(x, gt_box, gt_label,
                          gt_score=np.ones((2, 3), np.float32))
        _, l_half = _loss(x, gt_box, gt_label,
                          gt_score=np.full((2, 3), 0.5, np.float32))
        assert l_half.numpy().sum() < l_full.numpy().sum()

    def test_overfit_one_target(self):
        gt_box, gt_label = _gt(0.4, 0.6, 0.25, 0.25, 0)
        t = pt.to_tensor(_head(3, 0.1))
        t.stop_gradient = False
        opt = pt.optimizer.Adam(learning_rate=0.05, parameters=[t])
        first = None
        for i in range(60):
            loss = vops.yolo_loss(
                t, pt.to_tensor(gt_box), pt.to_tensor(gt_label),
                anchors=ANCHORS, anchor_mask=MASK, class_num=CLS,
                ignore_thresh=0.7, downsample_ratio=DOWN).sum()
            loss.backward()
            opt.step()
            opt.clear_grad()
            if first is None:
                first = float(loss)
        # converges to the BCE/label-smooth entropy floor (~0.12x start)
        assert float(loss) < first * 0.25, (first, float(loss))
