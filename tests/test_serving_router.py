"""Serving-tier survival: the multi-replica router + the engine's
graceful-degradation layer (paddle_tpu/serving/router.py + engine
deadlines/shedding/starvation guard).

The load-bearing properties:

* routing/failover may never change a token — a request served across
  a replica death finishes byte-identical to the sequential reference;
* overload degrades to FAST structured refusals (ShedRequest with a
  reason + the gauge values), never unbounded queue growth — the
  admitted requests' queue depth stays under the watermark throughout;
* every abnormal exit (deadline expiry, drain, shed, failover, replica
  death) frees all resources — pools come back with zero leaked blocks;
* hang (stale heartbeat) and crash (raise/exit) are DISTINCT eviction
  causes.

Tier-1 wiring of ``chaos_check --router`` lives here too.
"""
import io
import os
import time

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.distributed.launch.heartbeat import BeatWatch
from paddle_tpu.observability import metrics
from paddle_tpu.serving import LLMEngine, Router, ShedRequest
from paddle_tpu.text import GPTConfig, GPTForCausalLM
from paddle_tpu.text.generation import generate

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def gpt():
    pt.seed(0)
    cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=2,
                    num_heads=4, max_position_embeddings=64,
                    hidden_dropout=0.0, attention_dropout=0.0,
                    tensor_parallel=False)
    return GPTForCausalLM(cfg)


def _seq_ref(model, prompt, n, eos=None):
    out = generate(model, pt.to_tensor(np.asarray([prompt], "int64")),
                   max_new_tokens=n, eos_token_id=eos)
    return out.numpy()[0, len(prompt):].tolist()


def _factory(gpt, **overrides):
    kw = dict(num_blocks=24, block_size=4, max_running=8,
              prefill_chunk=16)
    kw.update(overrides)
    return lambda: LLMEngine(gpt, **kw)


# ===================================================================
# routing: least-loaded spread, session affinity
# ===================================================================
def test_router_least_loaded_spread_parity(gpt):
    rng = np.random.RandomState(3)
    prompts = [rng.randint(0, 64, size=n).tolist()
               for n in (5, 9, 4, 11, 7, 6)]
    refs = [_seq_ref(gpt, p, 6) for p in prompts]
    router = Router(_factory(gpt), replicas=2, heartbeat_timeout=30.0)
    rrs = [router.submit(p, max_new_tokens=6) for p in prompts]
    router.run()
    assert [rr.emitted for rr in rrs] == refs
    # least-loaded admission actually spread the work
    assert {rr.replica_names[0] for rr in rrs} == {"r0", "r1"}
    leaks = router.close()
    assert all(leaked == [] and bad == []
               for leaked, bad in leaks.values())


def test_router_session_affinity(gpt):
    reg = metrics.registry()
    base = reg.counter("router_affinity_hits_total").value
    router = Router(_factory(gpt), replicas=3, heartbeat_timeout=30.0)
    rrs = [router.submit([1, 2, 3, 4], max_new_tokens=4,
                         session_id="conv-1") for _ in range(3)]
    assert len({rr.replica_names[0] for rr in rrs}) == 1
    assert reg.counter("router_affinity_hits_total").value - base == 2
    # a different session is free to land elsewhere (no pinning leak)
    other = router.submit([5, 6, 7], max_new_tokens=4, session_id="c2")
    router.run()
    assert other.state == "finished"
    router.close()


# ===================================================================
# load shedding: structured refusals, bounded queue (the acceptance
# criterion: overload keeps admitted TTFT bounded, shed requests get a
# structured refusal and free all resources)
# ===================================================================
def test_engine_shed_queue_depth_watermark(gpt):
    reg = metrics.registry()
    base = reg.counter("serving_requests_shed_total",
                       reason="queue_depth").value
    eng = _factory(gpt, num_blocks=6, max_running=1,
                   shed_queue_depth=2)()
    admitted, shed = [], []
    for i in range(8):
        try:
            admitted.append(eng.add_request([1 + i] * 5,
                                            max_new_tokens=4))
        except ShedRequest as e:
            shed.append(e)
    # no step() has run yet, so nothing moved queue->running: the
    # queue takes `watermark` requests and every later submit sheds
    assert len(shed) == 6
    for e in shed:
        assert e.reason == "queue_depth"
        assert e.detail["queue_depth"] >= 2
        assert e.detail["watermark"] == 2
    assert reg.counter("serving_requests_shed_total",
                       reason="queue_depth").value - base == 6
    # the queue NEVER grows past the watermark while the backlog drains
    while eng.has_work:
        assert eng.scheduler.queue_depth <= 2
        eng.step()
    assert all(r.finish_reason == "length" for r in admitted)
    assert eng.pool.check_leaks() == ([], [])
    assert eng.pool.free_blocks == eng.pool.num_blocks


def test_engine_shed_free_blocks_watermark(gpt):
    eng = _factory(gpt, num_blocks=4, max_running=1,
                   shed_free_blocks=2)()
    a = eng.add_request([1] * 9, max_new_tokens=4)   # takes 3 blocks
    eng.step()
    b = eng.add_request([2] * 9, max_new_tokens=4)   # queues (no slot)
    with pytest.raises(ShedRequest) as ei:
        eng.add_request([3] * 9, max_new_tokens=4)
    assert ei.value.reason == "free_blocks"
    assert ei.value.detail["free_blocks"] < 2
    eng.run()
    assert a.finish_reason == "length" and b.finish_reason == "length"
    assert eng.pool.check_leaks() == ([], [])


def test_router_sheds_when_every_replica_refuses(gpt):
    router = Router(_factory(gpt, max_running=1, shed_queue_depth=1),
                    replicas=2, heartbeat_timeout=30.0)
    ok = []
    with pytest.raises(ShedRequest) as ei:
        for i in range(8):
            ok.append(router.submit([1 + i] * 4, max_new_tokens=4))
    assert ei.value.reason == "queue_depth"
    assert ei.value.detail["replicas_tried"] == 2
    # no steps ran between submissions: each replica's queue holds the
    # watermark's worth, then the ROUTER sheds (both replicas refused)
    assert len(ok) == 2
    router.run()
    assert all(rr.state == "finished" for rr in ok)
    router.close()


# ===================================================================
# deadlines: queue-wait and TTL expiry are clean finishes
# ===================================================================
def test_queue_deadline_expires_cleanly(gpt):
    reg = metrics.registry()
    base = reg.counter("serving_requests_expired_total",
                       where="queue").value
    eng = _factory(gpt, num_blocks=4, max_running=1)()
    done = []
    a = eng.add_request([1] * 9, max_new_tokens=6)      # hogs the slot
    b = eng.add_request([2] * 9, max_new_tokens=6,      # waits
                        queue_deadline_s=0.05,
                        on_finish=lambda r: done.append(r.id))
    t0 = time.monotonic()
    while eng.has_work and time.monotonic() - t0 < 30:
        eng.step()
    assert a.finish_reason == "length"
    assert b.finish_reason == "expired-queue"
    assert b.state == "expired"
    assert done == [b.id]
    assert reg.counter("serving_requests_expired_total",
                       where="queue").value - base == 1
    assert eng.pool.check_leaks() == ([], [])
    assert eng.pool.free_blocks == eng.pool.num_blocks


def test_ttl_expires_running_request_and_frees_blocks(gpt):
    reg = metrics.registry()
    base = reg.counter("serving_requests_expired_total",
                       where="ttl").value
    eng = _factory(gpt)()
    a = eng.add_request([1, 2, 3], max_new_tokens=50, ttl_s=0.02)
    b = eng.add_request([4, 5, 6], max_new_tokens=4)
    t0 = time.monotonic()
    while eng.has_work and time.monotonic() - t0 < 30:
        eng.step()
    assert a.finish_reason == "expired-ttl"
    assert len(a.generated) < 50            # cut off mid-generation
    assert b.finish_reason == "length"      # neighbors unaffected
    assert reg.counter("serving_requests_expired_total",
                       where="ttl").value - base == 1
    assert eng.pool.check_leaks() == ([], [])
    assert eng.pool.free_blocks == eng.pool.num_blocks


# ===================================================================
# failover building blocks: resume_tokens, cancel
# ===================================================================
def test_resume_tokens_continuation_parity(gpt):
    prompt = [7, 3, 9, 1, 5]
    ref = _seq_ref(gpt, prompt, 8)
    eng = _factory(gpt)()
    req = eng.add_request(prompt, max_new_tokens=8,
                          resume_tokens=ref[:3])
    eng.run()
    # the resumed request re-prefills prompt+resume and continues at
    # token 3 — the full stream is byte-identical to never moving
    assert req.generated == ref
    assert req.resumed


def test_resume_tokens_sampled_parity(gpt):
    """Per-(seed, position) sampling makes even SAMPLED streams
    resume-exact: the survivor re-derives the same draws."""
    prompt = [11, 4, 2, 8]
    kw = dict(max_new_tokens=8, do_sample=True, temperature=0.9,
              top_k=20, seed=42)
    eng = _factory(gpt)()
    full = eng.add_request(prompt, **kw)
    eng.run()
    resumed = eng.add_request(prompt, resume_tokens=full.generated[:4],
                              **kw)
    eng.run()
    assert resumed.generated == full.generated


def test_resume_tokens_validation(gpt):
    eng = _factory(gpt)()
    with pytest.raises(ValueError, match="nothing left"):
        eng.add_request([1, 2, 3], max_new_tokens=4,
                        resume_tokens=[5, 6, 7, 8])


def test_engine_cancel_frees_blocks(gpt):
    eng = _factory(gpt)()
    req = eng.add_request([1] * 6, max_new_tokens=50)
    eng.step()
    eng.step()
    assert req.block_table        # running, holding blocks
    eng.cancel(req)
    assert req.finish_reason == "cancelled"
    assert eng.pool.check_leaks() == ([], [])
    assert eng.pool.free_blocks == eng.pool.num_blocks
    eng.cancel(req)               # idempotent on settled requests


# ===================================================================
# starvation guard: repeated skips promote out of the victim pool
# ===================================================================
def test_starvation_promotion_counter_and_completion(gpt):
    reg = metrics.registry()
    base = reg.counter("serving_starvation_promotions_total").value
    prompts = [[1 + i] * 9 for i in range(3)]
    refs = [_seq_ref(gpt, p, 8) for p in prompts]
    # 6 blocks of 4 for three 17-token requests: sustained block
    # pressure -> repeated LIFO preemption; aging must promote rather
    # than livelock, and promotion may never change a token
    eng = _factory(gpt, num_blocks=6, max_running=3, promote_after=2)()
    reqs = [eng.add_request(p, max_new_tokens=8) for p in prompts]
    eng.run(max_steps=10_000)
    assert [r.generated for r in reqs] == refs
    assert reg.counter(
        "serving_starvation_promotions_total").value - base >= 1
    assert any(r.promoted for r in reqs)
    assert eng.pool.check_leaks() == ([], [])


# ===================================================================
# graceful shutdown: drain + close
# ===================================================================
def test_engine_drain_and_close(gpt):
    eng = _factory(gpt, max_running=2)()
    running = [eng.add_request([1 + i] * 5, max_new_tokens=4)
               for i in range(2)]
    eng.step()
    queued = eng.add_request([9] * 5, max_new_tokens=4)
    eng.scheduler.max_running = 2   # keep it queued
    summary = eng.drain(ttl_s=30.0)
    # draining: queued work expired immediately, running finished
    assert queued.finish_reason == "drained"
    assert all(r.finish_reason == "length" for r in running)
    assert summary["drained"] >= 1
    with pytest.raises(ShedRequest) as ei:
        eng.add_request([1, 2], max_new_tokens=2)
    assert ei.value.reason == "draining"
    leaks = eng.close()
    assert leaks == ([], [])
    assert eng.pool.k == [] and eng.pool.v == []
    with pytest.raises(RuntimeError, match="closed"):
        eng.add_request([1, 2], max_new_tokens=2)


def test_engine_drain_ttl_expires_running(gpt):
    eng = _factory(gpt)()
    req = eng.add_request([1] * 5, max_new_tokens=50)
    eng.step()
    eng.drain(ttl_s=0.0)          # budget exhausted immediately
    assert req.finish_reason == "drained"
    assert eng.pool.free_blocks == eng.pool.num_blocks


def test_router_drain_sheds_new_work(gpt):
    router = Router(_factory(gpt), replicas=2, heartbeat_timeout=30.0)
    rr = router.submit([1, 2, 3, 4], max_new_tokens=4)
    router.drain(ttl_s=30.0)
    assert rr.state == "finished"
    with pytest.raises(ShedRequest) as ei:
        router.submit([5, 6], max_new_tokens=2)
    assert ei.value.reason == "draining"
    router.close()


def test_client_callback_error_fails_only_that_request(gpt):
    """A broken client stream (on_token raises) must fail ITS request —
    never propagate into engine.step where the router would misread it
    as a replica crash and evict a healthy replica."""
    router = Router(_factory(gpt), replicas=2, heartbeat_timeout=30.0)

    def broken(rr, tok):
        raise BrokenPipeError("client went away")

    good_prompt = [2, 4, 6, 8]
    ref = _seq_ref(gpt, good_prompt, 5)
    bad_rr = router.submit([1, 3, 5], max_new_tokens=5, on_token=broken)
    ok_rr = router.submit(good_prompt, max_new_tokens=5)
    with pytest.warns(UserWarning, match="client callback"):
        router.run()
    assert bad_rr.state == "failed"
    assert bad_rr.finish_reason == "client_error"
    assert ok_rr.state == "finished" and ok_rr.emitted == ref
    # no eviction happened for a client-side failure
    assert [s.state for s in router._slots] == ["healthy", "healthy"]
    assert router.events == []
    leaks = router.close()
    assert all(leaked == [] and bad == []
               for leaked, bad in leaks.values())


# ===================================================================
# heartbeat: BeatWatch staleness semantics (watcher-clock based)
# ===================================================================
def test_beatwatch_staleness(tmp_path):
    clock = {"t": 100.0}
    path = str(tmp_path / "hb")
    w = BeatWatch(path, timeout=5.0, clock=lambda: clock["t"])
    # missing file: grace period, then stale
    assert not w.stale()
    clock["t"] += 6.0
    assert w.stale()
    # a beat (mtime change) resets the window
    with open(path, "w"):
        pass
    assert not w.stale()
    clock["t"] += 4.0
    assert not w.stale()          # within timeout
    clock["t"] += 2.0
    assert w.stale()              # silent past timeout
    os.utime(path, (1, 12345))    # fresh beat observed -> alive again
    assert not w.stale()
    assert w.silent_for == 0.0


# ===================================================================
# tier-1 wiring of the survival drill
# ===================================================================
def test_chaos_check_router_inprocess():
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "chaos_check_router", os.path.join(REPO, "tools",
                                           "chaos_check.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    buf = io.StringIO()
    assert mod.run_router(out=buf) == 0, buf.getvalue()
    out = buf.getvalue()
    assert "crash-loop abandon" in out
    assert "stale heartbeat" in out
