"""`import paddle` drop-in alias (reference: the whole point — user code
written against python/paddle/* runs unmodified on the TPU framework).

The alias package (paddle/__init__.py) must hand back the SAME module
objects as paddle_tpu.* so registries/isinstance stay coherent.
"""
import importlib
import sys

import numpy as np


def test_module_identity():
    import paddle
    import paddle_tpu

    assert paddle.nn is paddle_tpu.nn
    assert paddle.Tensor is paddle_tpu.Tensor
    assert paddle.distributed is paddle_tpu.distributed
    # deep submodule import through the meta-path finder
    f = importlib.import_module("paddle.nn.functional")
    assert f is paddle_tpu.nn.functional
    assert sys.modules["paddle.nn.functional"] is f


def test_from_import_forms():
    from paddle.io import DataLoader, TensorDataset  # noqa: F401
    from paddle.nn import Linear  # noqa: F401
    from paddle.optimizer import AdamW  # noqa: F401
    from paddle.distributed import fleet  # noqa: F401
    from paddle.vision import transforms  # noqa: F401
    import paddle.incubate.nn  # noqa: F401
    import paddle.static  # noqa: F401


def test_verbatim_reference_training_script():
    """A reference-style dygraph train loop, written only against `paddle`,
    runs unmodified and the loss decreases."""
    import paddle
    import paddle.nn as nn
    import paddle.nn.functional as F

    class Net(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc1 = nn.Linear(4, 16)
            self.fc2 = nn.Linear(16, 2)

        def forward(self, x):
            return self.fc2(F.relu(self.fc1(x)))

    paddle.seed(0)
    net = Net()
    opt = paddle.optimizer.Adam(learning_rate=1e-2,
                                parameters=net.parameters())
    xs = np.random.RandomState(0).randn(32, 4).astype("float32")
    x = paddle.to_tensor(xs)
    y = paddle.to_tensor((xs[:, 0] > 0).astype("int64"))  # learnable rule

    losses = []
    for _ in range(30):
        logits = net(x)
        loss = F.cross_entropy(logits, y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.5, losses[::10]


def test_verbatim_fleet_script():
    """Reference-style fleet collective init + distributed_model path."""
    import paddle
    from paddle.distributed import fleet

    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 1, "mp_degree": 1, "pp_degree": 1}
    fleet.init(is_collective=True, strategy=strategy)

    model = paddle.nn.Linear(8, 8)
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=model.parameters())
    model = fleet.distributed_model(model)
    opt = fleet.distributed_optimizer(opt)

    x = paddle.ones([2, 8])
    loss = model(x).mean()
    loss.backward()
    opt.step()
    opt.clear_grad()
