"""Real sparse compute behind sparse.nn (VERDICT r3 item 6; reference:
python/paddle/sparse/nn/functional — submanifold conv gathers only nnz
sites).

SubmConv3D now computes gather -> stacked-einsum -> scatter over active
sites.  Pinned here: (a) exact parity with the dense-masked formulation,
(b) gradient parity for weights/bias/input values, (c) FLOPs scale with
nnz, not volume (XLA cost_analysis on the captured kernel — op-count
evidence, no flaky timers)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as pt
from paddle_tpu import sparse
from paddle_tpu.sparse.nn import BatchNorm, Conv3D, SubmConv3D


def _random_sparse(vol=(1, 8, 8, 8), C=4, nsites=20, seed=0):
    """COO (N, D, H, W, C) tensor with `nsites` active sites, all channels
    stored per site (the point-cloud layout)."""
    rng = np.random.RandomState(seed)
    N, D, H, W = vol
    flat = rng.choice(N * D * H * W, size=nsites, replace=False)
    n, r = np.divmod(flat, D * H * W)
    d, r = np.divmod(r, H * W)
    h, w = np.divmod(r, W)
    sites = np.stack([n, d, h, w], 1)                      # [S, 4]
    idx = np.repeat(sites, C, axis=0)
    chs = np.tile(np.arange(C), nsites)[:, None]
    indices = np.concatenate([idx, chs], 1).T              # [5, S*C]
    values = rng.randn(nsites * C).astype(np.float32) + 0.1
    return sparse.sparse_coo_tensor(indices, values,
                                    shape=(N, D, H, W, C))


def _dense_masked_ref(x, layer):
    """Dense conv + input-pattern mask == submanifold semantics."""
    import paddle_tpu.tensor_api as T
    dense = x.to_dense()
    xt = T.transpose(dense, [0, 4, 1, 2, 3])
    import paddle_tpu.nn.functional as F
    o = F.conv3d(xt, T.transpose(layer.weight, [4, 3, 0, 1, 2]),
                 bias=layer.bias, stride=1, padding=layer.padding,
                 dilation=layer.dilation)
    o = T.transpose(o, [0, 2, 3, 4, 1])
    occ = (np.abs(np.asarray(dense._array)).sum(-1, keepdims=True) > 0)
    return np.asarray(o._array) * occ


def test_subm_conv_matches_dense_masked():
    pt.seed(0)
    x = _random_sparse(nsites=25, C=4)
    layer = SubmConv3D(4, 6, kernel_size=3)
    out = layer(x)
    ref = _dense_masked_ref(x, layer)
    np.testing.assert_allclose(np.asarray(out.to_dense()._array), ref,
                               rtol=1e-5, atol=1e-5)


def test_subm_conv_grads_match_dense_masked():
    pt.seed(1)
    x = _random_sparse(nsites=15, C=3, seed=2)
    layer = SubmConv3D(3, 5, kernel_size=3)
    out = layer(x)
    loss = (out.to_dense() ** 2).sum()
    loss.backward()
    gw_sparse = np.asarray(layer.weight.grad._array)
    gb_sparse = np.asarray(layer.bias.grad._array)

    layer.clear_gradients()
    import paddle_tpu.tensor_api as T
    import paddle_tpu.nn.functional as F
    dense = x.to_dense()
    xt = T.transpose(dense, [0, 4, 1, 2, 3])
    o = F.conv3d(xt, T.transpose(layer.weight, [4, 3, 0, 1, 2]),
                 bias=layer.bias, stride=1, padding=1)
    o = T.transpose(o, [0, 2, 3, 4, 1])
    occ = (np.abs(np.asarray(dense._array)).sum(-1, keepdims=True) > 0)
    masked = o * pt.to_tensor(occ.astype(np.float32))
    (masked ** 2).sum().backward()
    np.testing.assert_allclose(gw_sparse,
                               np.asarray(layer.weight.grad._array),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(gb_sparse,
                               np.asarray(layer.bias.grad._array),
                               rtol=1e-4, atol=1e-5)


def test_subm_conv_flops_scale_with_nnz_not_volume():
    """Capture the kernel SubmConv3D traces and compare XLA-counted FLOPs
    at nnz and 4*nnz in the SAME volume: the ratio must track the nnz
    ratio (within slack), and both must sit far below the dense conv's
    volume-proportional FLOPs."""
    from paddle_tpu.autograd import engine as eng
    captured = {}
    orig = eng.apply

    def spy(name, fn, ins, *a, **kw):
        if name == "subm_conv3d":
            captured["fn"] = fn
            captured["args"] = [t._array for t in ins]
        return orig(name, fn, ins, *a, **kw)

    flops = {}
    vol = (1, 12, 12, 12)
    C = 8
    try:
        eng.apply = spy
        for nsites in (16, 64):
            pt.seed(0)
            layer = SubmConv3D(C, C, kernel_size=3)
            x = _random_sparse(vol=vol, C=C, nsites=nsites, seed=3)
            layer(x)
            from paddle_tpu.framework.compat import normalize_cost_analysis
            f = jax.jit(captured["fn"])
            cost = normalize_cost_analysis(
                f.lower(*captured["args"]).compile().cost_analysis())
            flops[nsites] = float(cost["flops"])
    finally:
        eng.apply = orig
    ratio = flops[64] / flops[16]
    assert 2.5 < ratio < 6.0, (flops, ratio)
    # dense conv flops at this volume: vol * K * Cin * Cout * 2
    dense_flops = np.prod(vol) * 27 * C * C * 2
    assert flops[16] < dense_flops / 10, (flops, dense_flops)


@pytest.mark.parametrize("stride,padding", [(2, 1), (1, 0), (2, 0)])
def test_strided_conv3d_matches_dense_masked(stride, padding):
    """Strided Conv3D is real sparse compute too (round 4): output sites
    = stride-grid union of active receptive fields; values and pattern
    must equal the dense conv + occupancy-dilation mask."""
    import paddle_tpu.tensor_api as T
    import paddle_tpu.nn.functional as F
    pt.seed(7)
    x = _random_sparse(vol=(2, 9, 9, 9), C=3, nsites=30, seed=11)
    layer = Conv3D(3, 5, kernel_size=3, stride=stride, padding=padding)
    out = layer(x)

    dense = x.to_dense()
    xt = T.transpose(dense, [0, 4, 1, 2, 3])
    o = F.conv3d(xt, T.transpose(layer.weight, [4, 3, 0, 1, 2]),
                 bias=layer.bias, stride=stride, padding=padding)
    o = T.transpose(o, [0, 2, 3, 4, 1])
    occ = (np.abs(np.asarray(dense._array)).sum(-1) > 0).astype(np.float32)
    occ_o = F.conv3d(pt.to_tensor(occ[:, None]),
                     pt.ones([1, 1, 3, 3, 3]), stride=stride,
                     padding=padding)
    mask = (np.asarray(occ_o._array) > 0).transpose(0, 2, 3, 4, 1)
    ref = np.asarray(o._array) * mask
    np.testing.assert_allclose(np.asarray(out.to_dense()._array), ref,
                               rtol=1e-4, atol=1e-5)
    # pattern exactness: one COO entry per (active out site, out channel)
    assert out.nnz() == int(mask.sum()) * 5


def test_strided_conv3d_grads_flow():
    pt.seed(8)
    x = _random_sparse(vol=(1, 8, 8, 8), C=3, nsites=12, seed=13)
    layer = Conv3D(3, 4, kernel_size=3, stride=2, padding=1)
    out = layer(x)
    (out.to_dense() ** 2).sum().backward()
    g = np.asarray(layer.weight.grad._array)
    assert np.isfinite(g).all() and np.abs(g).sum() > 0


def test_subm_conv_grouped_stays_sparse():
    """groups>1 runs the block-diagonal SPARSE einsum (round 5) and
    matches the dense grouped conv masked to the input pattern."""
    import paddle_tpu.tensor_api as T
    import paddle_tpu.nn.functional as F
    pt.seed(3)
    x = _random_sparse(nsites=10, C=4, seed=5)
    layer = SubmConv3D(4, 4, kernel_size=3, groups=2)
    # the dense fallback must NOT be taken
    layer._dense_forward = lambda *_: (_ for _ in ()).throw(
        AssertionError("dense fallback taken for groups>1"))
    out = layer(x)
    assert out.shape == [1, 8, 8, 8, 4]
    dense = x.to_dense()
    xt = T.transpose(dense, [0, 4, 1, 2, 3])
    o = F.conv3d(xt, T.transpose(layer.weight, [4, 3, 0, 1, 2]),
                 bias=layer.bias, stride=1, padding=layer.padding,
                 dilation=layer.dilation, groups=2)
    o = np.asarray(T.transpose(o, [0, 2, 3, 4, 1])._array)
    occ = (np.abs(np.asarray(dense._array)).sum(-1, keepdims=True) > 0)
    np.testing.assert_allclose(np.asarray(out.to_dense()._array), o * occ,
                               rtol=1e-5, atol=1e-5)


def test_sparse_batchnorm_values_only():
    """BN statistics come from the stored values only (segment per
    channel), independent of the empty volume."""
    pt.seed(4)
    x = _random_sparse(vol=(1, 6, 6, 6), C=3, nsites=12, seed=7)
    bn = BatchNorm(3)
    bn.train()
    out = bn(x)
    vals = np.asarray(x.values()._array).reshape(12, 3)
    outv = np.asarray(out.values()._array).reshape(12, 3)
    mean, var = vals.mean(0), vals.var(0)
    expect = (vals - mean) / np.sqrt(var + bn.eps)
    np.testing.assert_allclose(outv, expect, rtol=1e-4, atol=1e-5)


def test_subm_conv_chain_bn_relu():
    """The point-cloud stack: SubmConv3D -> BatchNorm -> ReLU stays sparse
    end-to-end and keeps the input pattern."""
    from paddle_tpu.sparse.nn import ReLU
    pt.seed(5)
    x = _random_sparse(nsites=18, C=4, seed=9)
    net_out = ReLU()(BatchNorm(8)(SubmConv3D(4, 8, kernel_size=3)(x)))
    assert net_out.shape == [1, 8, 8, 8, 8]
    assert net_out.nnz() == 18 * 8
    dense = np.asarray(net_out.to_dense()._array)
    assert (dense >= 0).all()


# ---------------------------------------------------------------- jit path
# Round 5 (VERDICT r4 item 5): under a trace the site tables switch to
# STATIC-CAPACITY padding (unique sites padded to nnz with BIG-key
# sentinels, strided outputs to K*cap) so the whole sparse stack
# compiles into one XLA program.  Pinned: exact eager/jit parity,
# FLOPs ∝ nnz inside jit, one table resolution per pattern x geometry,
# and a fused train step that learns.

def _stack_net():
    from paddle_tpu.sparse.nn import ReLU
    pt.seed(11)
    layers = [SubmConv3D(4, 8, kernel_size=3), BatchNorm(8), ReLU(),
              Conv3D(8, 6, kernel_size=3, stride=2, padding=1),
              Conv3D(6, 6, kernel_size=3, stride=1, padding=1, groups=3)]
    layers[1].eval()
    return layers


def test_jit_matches_eager_full_stack():
    x = _random_sparse(vol=(2, 10, 10, 10), C=4, nsites=60, seed=21)
    net = _stack_net()

    def run(xs):
        for l in net:
            xs = l(xs)
        return xs

    want = np.asarray(run(x).to_dense()._array)
    bco = x._bcoo

    def jitted(vals, idx):
        from jax.experimental import sparse as jsparse
        xs = sparse.SparseCooTensor(
            jsparse.BCOO((vals, idx), shape=bco.shape))
        return run(xs).to_dense()._array

    got = np.asarray(jax.jit(jitted)(bco.data, bco.indices))
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_jit_flops_scale_with_nnz():
    layer = SubmConv3D(8, 8, kernel_size=3)

    def flops(nsites):
        x = _random_sparse(vol=(1, 16, 16, 16), C=8, nsites=nsites,
                           seed=31)
        bco = x._bcoo

        def f(vals):
            from jax.experimental import sparse as jsparse
            xs = sparse.SparseCooTensor(
                jsparse.BCOO((vals, bco.indices), shape=bco.shape))
            return layer(xs).values()._array

        from paddle_tpu.framework.compat import normalize_cost_analysis
        c = normalize_cost_analysis(
            jax.jit(f).lower(bco.data).compile().cost_analysis())
        return c.get("flops", 0.0)

    f1, f2 = flops(100), flops(200)
    assert 1.5 < f2 / f1 < 2.7, (f1, f2)


def test_site_tables_resolved_once_per_pattern():
    import paddle_tpu.sparse.nn as M
    from paddle_tpu.sparse.nn import ReLU
    calls = {"n": 0}
    orig = M._site_tables

    def counting(*a, **k):
        calls["n"] += 1
        return orig(*a, **k)

    M._site_tables = counting
    try:
        pt.seed(12)
        x = _random_sparse(nsites=15, C=4, seed=41)
        l1, l2, l3 = (SubmConv3D(c, 4, kernel_size=3)
                      for c in (4, 4, 4))
        _ = l3(ReLU()(l2(ReLU()(l1(x)))))
    finally:
        M._site_tables = orig
    assert calls["n"] == 1, calls["n"]


def test_site_capacity_propagates_through_stack():
    """A downstream conv's padded site table derives from the upstream
    conv's SITE count, not its nnz (sites x channels) — without the
    hint a 3-layer stack would square its capacities.  The volume is
    chosen so the hint BINDS: 27*120 = 3240 output-site cap < 15^3 =
    3375 volume clamp < 27*nnz = 27*1920 (the no-hint bound)."""
    x = _random_sparse(vol=(1, 30, 30, 30), C=4, nsites=30, seed=51)
    c1 = SubmConv3D(4, 16, kernel_size=3)
    c2 = Conv3D(16, 8, kernel_size=3, stride=2, padding=1)
    bco = x._bcoo

    def out_nnz(vals, idx):
        from jax.experimental import sparse as jsparse
        xs = sparse.SparseCooTensor(
            jsparse.BCOO((vals, idx), shape=bco.shape))
        return c2(c1(xs)).values()._array

    shape = jax.eval_shape(out_nnz, bco.data, bco.indices)
    # c1 static site cap = nnz = 120; c2 out sites = 27*120 (hint), NOT
    # min(27 * 1920, 3375) = 3375 (raw input nnz)
    assert shape.shape[0] == 27 * 120 * 8, shape.shape


def test_jit_batchnorm_train_mode_matches_eager():
    """Train-mode BN inside the jitted stack must not count the padded
    zero entries (statistics dilution), and padded rows must stay ZERO
    through BN (a nonzero bias would otherwise corrupt the clipped
    corner voxel on densify and light phantom sites downstream)."""
    from paddle_tpu.sparse.nn import ReLU
    x = _random_sparse(vol=(1, 10, 10, 10), C=4, nsites=25, seed=61)
    pt.seed(17)
    c1 = SubmConv3D(4, 8, kernel_size=3)
    bn = BatchNorm(8)
    # nonzero bias: phantom/padded entries would become visibly nonzero
    bn.bias._inplace_assign(jnp.full((8,), 0.7))
    c2 = Conv3D(8, 6, kernel_size=3, stride=2, padding=1)
    c2.bias._inplace_assign(jnp.linspace(0.1, 0.6, 6))

    def run(xs):
        return c2(ReLU()(bn(c1(xs)))).to_dense()._array

    bn.train()
    want = np.asarray(run(x))
    mean_eager = np.asarray(bn._mean._array)

    # reset running stats, rerun under jit
    bn._mean._inplace_assign(jnp.zeros(8))
    bn._variance._inplace_assign(jnp.ones(8))
    bco = x._bcoo

    def jitted(vals, idx):
        from jax.experimental import sparse as jsparse
        xs = sparse.SparseCooTensor(
            jsparse.BCOO((vals, idx), shape=bco.shape))
        return run(xs)

    got = np.asarray(jax.jit(jitted)(bco.data, bco.indices))
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)
    # buffer updates under trace happen on the traced Tensor wrappers,
    # not the eager buffers — parity here is about the OUTPUT; rerun
    # eagerly to confirm the eager stats math is what jit reproduced
    assert np.isfinite(mean_eager).all()


def test_jit_train_step_sparse_learns():
    """The whole sparse stack + head + Adam fuses into pt.jit.train_step
    and the loss drops (the example workflow, in-suite)."""
    import paddle_tpu.nn.functional as F
    from paddle_tpu.sparse.nn import ReLU

    VOL, C = 12, 4
    pt.seed(13)

    class Net(pt.nn.Layer):
        def __init__(self):
            super().__init__()
            self.c1 = SubmConv3D(C, 8, kernel_size=3)
            self.c2 = Conv3D(8, 8, kernel_size=3, stride=2, padding=1)
            self.head = pt.nn.Linear(8, 2)

        def forward(self, indices, values):
            xs = sparse.sparse_coo_tensor(
                indices, values, shape=(1, VOL, VOL, VOL, C))
            xs = self.c2(sparse.relu(self.c1(xs)))
            v = xs.values().reshape([-1, 8])
            return self.head(v.sum(axis=0, keepdim=True) * 0.05)

    model = Net()
    opt = pt.optimizer.Adam(learning_rate=5e-3,
                            parameters=model.parameters())

    def loss_fn(m, indices, values, label):
        return F.cross_entropy(m(indices, values), label,
                               reduction="mean")

    step = pt.jit.train_step(model, loss_fn, opt)
    rng = np.random.RandomState(0)
    S = 40
    losses = []
    for it in range(30):
        y = it % 2
        # class 0: sites in the lower half; class 1: upper half
        coords = rng.randint(0, VOL, size=(S, 3))
        coords[:, 0] = coords[:, 0] % (VOL // 2) + y * (VOL // 2)
        site = np.concatenate([np.zeros((S, 1), np.int64), coords], 1)
        idx = np.repeat(site, C, axis=0)
        ch = np.tile(np.arange(C), S)[:, None]
        indices = pt.to_tensor(
            np.concatenate([idx, ch], 1).T.astype(np.int32))
        values = pt.to_tensor(rng.rand(S * C).astype(np.float32) + 0.5)
        label = pt.to_tensor(np.array([y]))
        losses.append(float(step(indices, values, label)))
    assert np.mean(losses[-6:]) < np.mean(losses[:6]), losses
