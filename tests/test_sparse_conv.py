"""Real sparse compute behind sparse.nn (VERDICT r3 item 6; reference:
python/paddle/sparse/nn/functional — submanifold conv gathers only nnz
sites).

SubmConv3D now computes gather -> stacked-einsum -> scatter over active
sites.  Pinned here: (a) exact parity with the dense-masked formulation,
(b) gradient parity for weights/bias/input values, (c) FLOPs scale with
nnz, not volume (XLA cost_analysis on the captured kernel — op-count
evidence, no flaky timers)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as pt
from paddle_tpu import sparse
from paddle_tpu.sparse.nn import BatchNorm, Conv3D, SubmConv3D


def _random_sparse(vol=(1, 8, 8, 8), C=4, nsites=20, seed=0):
    """COO (N, D, H, W, C) tensor with `nsites` active sites, all channels
    stored per site (the point-cloud layout)."""
    rng = np.random.RandomState(seed)
    N, D, H, W = vol
    flat = rng.choice(N * D * H * W, size=nsites, replace=False)
    n, r = np.divmod(flat, D * H * W)
    d, r = np.divmod(r, H * W)
    h, w = np.divmod(r, W)
    sites = np.stack([n, d, h, w], 1)                      # [S, 4]
    idx = np.repeat(sites, C, axis=0)
    chs = np.tile(np.arange(C), nsites)[:, None]
    indices = np.concatenate([idx, chs], 1).T              # [5, S*C]
    values = rng.randn(nsites * C).astype(np.float32) + 0.1
    return sparse.sparse_coo_tensor(indices, values,
                                    shape=(N, D, H, W, C))


def _dense_masked_ref(x, layer):
    """Dense conv + input-pattern mask == submanifold semantics."""
    import paddle_tpu.tensor_api as T
    dense = x.to_dense()
    xt = T.transpose(dense, [0, 4, 1, 2, 3])
    import paddle_tpu.nn.functional as F
    o = F.conv3d(xt, T.transpose(layer.weight, [4, 3, 0, 1, 2]),
                 bias=layer.bias, stride=1, padding=layer.padding,
                 dilation=layer.dilation)
    o = T.transpose(o, [0, 2, 3, 4, 1])
    occ = (np.abs(np.asarray(dense._array)).sum(-1, keepdims=True) > 0)
    return np.asarray(o._array) * occ


def test_subm_conv_matches_dense_masked():
    pt.seed(0)
    x = _random_sparse(nsites=25, C=4)
    layer = SubmConv3D(4, 6, kernel_size=3)
    out = layer(x)
    ref = _dense_masked_ref(x, layer)
    np.testing.assert_allclose(np.asarray(out.to_dense()._array), ref,
                               rtol=1e-5, atol=1e-5)


def test_subm_conv_grads_match_dense_masked():
    pt.seed(1)
    x = _random_sparse(nsites=15, C=3, seed=2)
    layer = SubmConv3D(3, 5, kernel_size=3)
    out = layer(x)
    loss = (out.to_dense() ** 2).sum()
    loss.backward()
    gw_sparse = np.asarray(layer.weight.grad._array)
    gb_sparse = np.asarray(layer.bias.grad._array)

    layer.clear_gradients()
    import paddle_tpu.tensor_api as T
    import paddle_tpu.nn.functional as F
    dense = x.to_dense()
    xt = T.transpose(dense, [0, 4, 1, 2, 3])
    o = F.conv3d(xt, T.transpose(layer.weight, [4, 3, 0, 1, 2]),
                 bias=layer.bias, stride=1, padding=1)
    o = T.transpose(o, [0, 2, 3, 4, 1])
    occ = (np.abs(np.asarray(dense._array)).sum(-1, keepdims=True) > 0)
    masked = o * pt.to_tensor(occ.astype(np.float32))
    (masked ** 2).sum().backward()
    np.testing.assert_allclose(gw_sparse,
                               np.asarray(layer.weight.grad._array),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(gb_sparse,
                               np.asarray(layer.bias.grad._array),
                               rtol=1e-4, atol=1e-5)


def test_subm_conv_flops_scale_with_nnz_not_volume():
    """Capture the kernel SubmConv3D traces and compare XLA-counted FLOPs
    at nnz and 4*nnz in the SAME volume: the ratio must track the nnz
    ratio (within slack), and both must sit far below the dense conv's
    volume-proportional FLOPs."""
    from paddle_tpu.autograd import engine as eng
    captured = {}
    orig = eng.apply

    def spy(name, fn, ins, *a, **kw):
        if name == "subm_conv3d":
            captured["fn"] = fn
            captured["args"] = [t._array for t in ins]
        return orig(name, fn, ins, *a, **kw)

    flops = {}
    vol = (1, 12, 12, 12)
    C = 8
    try:
        eng.apply = spy
        for nsites in (16, 64):
            pt.seed(0)
            layer = SubmConv3D(C, C, kernel_size=3)
            x = _random_sparse(vol=vol, C=C, nsites=nsites, seed=3)
            layer(x)
            f = jax.jit(captured["fn"])
            cost = f.lower(*captured["args"]).compile().cost_analysis()
            if isinstance(cost, list):  # older jax returns [dict]
                cost = cost[0]
            flops[nsites] = float(cost["flops"])
    finally:
        eng.apply = orig
    ratio = flops[64] / flops[16]
    assert 2.5 < ratio < 6.0, (flops, ratio)
    # dense conv flops at this volume: vol * K * Cin * Cout * 2
    dense_flops = np.prod(vol) * 27 * C * C * 2
    assert flops[16] < dense_flops / 10, (flops, dense_flops)


@pytest.mark.parametrize("stride,padding", [(2, 1), (1, 0), (2, 0)])
def test_strided_conv3d_matches_dense_masked(stride, padding):
    """Strided Conv3D is real sparse compute too (round 4): output sites
    = stride-grid union of active receptive fields; values and pattern
    must equal the dense conv + occupancy-dilation mask."""
    import paddle_tpu.tensor_api as T
    import paddle_tpu.nn.functional as F
    pt.seed(7)
    x = _random_sparse(vol=(2, 9, 9, 9), C=3, nsites=30, seed=11)
    layer = Conv3D(3, 5, kernel_size=3, stride=stride, padding=padding)
    out = layer(x)

    dense = x.to_dense()
    xt = T.transpose(dense, [0, 4, 1, 2, 3])
    o = F.conv3d(xt, T.transpose(layer.weight, [4, 3, 0, 1, 2]),
                 bias=layer.bias, stride=stride, padding=padding)
    o = T.transpose(o, [0, 2, 3, 4, 1])
    occ = (np.abs(np.asarray(dense._array)).sum(-1) > 0).astype(np.float32)
    occ_o = F.conv3d(pt.to_tensor(occ[:, None]),
                     pt.ones([1, 1, 3, 3, 3]), stride=stride,
                     padding=padding)
    mask = (np.asarray(occ_o._array) > 0).transpose(0, 2, 3, 4, 1)
    ref = np.asarray(o._array) * mask
    np.testing.assert_allclose(np.asarray(out.to_dense()._array), ref,
                               rtol=1e-4, atol=1e-5)
    # pattern exactness: one COO entry per (active out site, out channel)
    assert out.nnz() == int(mask.sum()) * 5


def test_strided_conv3d_grads_flow():
    pt.seed(8)
    x = _random_sparse(vol=(1, 8, 8, 8), C=3, nsites=12, seed=13)
    layer = Conv3D(3, 4, kernel_size=3, stride=2, padding=1)
    out = layer(x)
    (out.to_dense() ** 2).sum().backward()
    g = np.asarray(layer.weight.grad._array)
    assert np.isfinite(g).all() and np.abs(g).sum() > 0


def test_subm_conv_grouped_or_strided_falls_back():
    """groups>1 routes through the dense-masked path and still matches."""
    pt.seed(3)
    x = _random_sparse(nsites=10, C=4, seed=5)
    layer = SubmConv3D(4, 4, kernel_size=3, groups=2)
    out = layer(x)
    assert out.shape == [1, 8, 8, 8, 4]


def test_sparse_batchnorm_values_only():
    """BN statistics come from the stored values only (segment per
    channel), independent of the empty volume."""
    pt.seed(4)
    x = _random_sparse(vol=(1, 6, 6, 6), C=3, nsites=12, seed=7)
    bn = BatchNorm(3)
    bn.train()
    out = bn(x)
    vals = np.asarray(x.values()._array).reshape(12, 3)
    outv = np.asarray(out.values()._array).reshape(12, 3)
    mean, var = vals.mean(0), vals.var(0)
    expect = (vals - mean) / np.sqrt(var + bn.eps)
    np.testing.assert_allclose(outv, expect, rtol=1e-4, atol=1e-5)


def test_subm_conv_chain_bn_relu():
    """The point-cloud stack: SubmConv3D -> BatchNorm -> ReLU stays sparse
    end-to-end and keeps the input pattern."""
    from paddle_tpu.sparse.nn import ReLU
    pt.seed(5)
    x = _random_sparse(nsites=18, C=4, seed=9)
    net_out = ReLU()(BatchNorm(8)(SubmConv3D(4, 8, kernel_size=3)(x)))
    assert net_out.shape == [1, 8, 8, 8, 8]
    assert net_out.nnz() == 18 * 8
    dense = np.asarray(net_out.to_dense()._array)
    assert (dense >= 0).all()
