"""ERNIE-3.0 task heads + presets (reference: PaddleNLP ernie)."""
import numpy as np

import paddle_tpu as pt
from paddle_tpu.text import (
    ErnieForMaskedLM, ErnieForPretraining, ErnieForQuestionAnswering,
    ErnieForTokenClassification, ernie_config_from_preset,
)


def _cfg():
    return ernie_config_from_preset(
        "ernie-3.0-nano-zh", vocab_size=128, max_position_embeddings=64)


def test_token_classification_and_qa():
    pt.seed(0)
    ids = pt.randint(0, 128, [2, 10])
    tok = ErnieForTokenClassification(_cfg(), num_classes=7)
    assert tok(ids).shape == [2, 10, 7]
    qa = ErnieForQuestionAnswering(_cfg())
    start, end = qa(ids)
    assert start.shape == [2, 10] and end.shape == [2, 10]


def test_mlm_tied_embeddings_and_pretraining():
    pt.seed(1)
    ids = pt.randint(0, 128, [2, 8])
    mlm = ErnieForMaskedLM(_cfg())
    logits = mlm(ids)
    assert logits.shape == [2, 8, 128]
    # the decoder must be TIED to the word embedding (no duplicate weight)
    emb_id = id(mlm.ernie.bert.embeddings.word_embeddings.weight)
    assert not any(
        id(p) != emb_id and p.shape == [128, 312]
        for _, p in mlm.lm_head.named_parameters())
    loss = pt.nn.functional.cross_entropy(logits, ids)
    loss.backward()
    assert mlm.ernie.bert.embeddings.word_embeddings.weight.grad is not None

    pre = ErnieForPretraining(_cfg())
    ml, sop = pre(ids)
    assert ml.shape == [2, 8, 128] and sop.shape == [2, 2]


def test_preset_table_shapes():
    cfg = ernie_config_from_preset("ernie-3.0-base-zh")
    assert cfg.hidden_size == 768 and cfg.num_hidden_layers == 12
