"""deform_conv2d + affine_grid (reference: paddle.vision.ops.deform_conv2d,
paddle.nn.functional.affine_grid)."""
import numpy as np
import torch

import paddle_tpu as pt
import paddle_tpu.nn.functional as F
from paddle_tpu.vision.ops import DeformConv2D, deform_conv2d


def test_deform_conv_zero_offset_equals_conv():
    rng = np.random.RandomState(0)
    x = pt.to_tensor(rng.randn(2, 4, 8, 8).astype(np.float32))
    w = pt.to_tensor(rng.randn(6, 4, 3, 3).astype(np.float32))
    zero_off = pt.zeros([2, 18, 8, 8])
    got = deform_conv2d(x, zero_off, w, padding=1).numpy()
    want = F.conv2d(x, w, padding=1).numpy()
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)


def test_deform_conv_integer_shift():
    rng = np.random.RandomState(1)
    x = pt.to_tensor(rng.randn(2, 4, 8, 8).astype(np.float32))
    w = pt.to_tensor(rng.randn(6, 4, 3, 3).astype(np.float32))
    off = np.zeros((2, 18, 8, 8), np.float32)
    off[:, 1::2] = 1.0  # +1 x-shift for every tap
    got = deform_conv2d(x, pt.to_tensor(off), w, padding=1).numpy()
    xs = np.zeros_like(x.numpy())
    xs[:, :, :, :-1] = x.numpy()[:, :, :, 1:]
    want = F.conv2d(pt.to_tensor(xs), w, padding=1).numpy()
    np.testing.assert_allclose(got[:, :, 1:-1, 1:-2],
                               want[:, :, 1:-1, 1:-2],
                               rtol=2e-4, atol=2e-4)


def test_deform_conv_layer_mask_and_grads():
    pt.seed(2)
    layer = DeformConv2D(4, 6, 3, padding=1)
    x = pt.randn([2, 4, 8, 8])
    x.stop_gradient = False
    offset = pt.zeros([2, 18, 8, 8])
    offset.stop_gradient = False
    mask = pt.ones([2, 9, 8, 8])
    out = layer(x, offset, mask)
    assert out.shape == [2, 6, 8, 8]
    out.mean().backward()
    assert x.grad is not None and offset.grad is not None
    assert layer.weight.grad is not None


def test_affine_grid_matches_torch():
    theta = np.array([[[1.0, 0.2, 0.1], [0.0, 0.9, -0.3]],
                      [[0.8, 0.0, 0.0], [0.1, 1.1, 0.2]]], np.float32)
    for ac in (True, False):
        got = F.affine_grid(pt.to_tensor(theta), [2, 3, 5, 7],
                            align_corners=ac).numpy()
        want = torch.nn.functional.affine_grid(
            torch.tensor(theta), (2, 3, 5, 7), align_corners=ac).numpy()
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_affine_grid_differentiable_and_batch_check():
    import pytest
    theta = pt.to_tensor(np.array([[[1.0, 0, 0], [0, 1.0, 0]]],
                                  np.float32))
    theta.stop_gradient = False
    grid = F.affine_grid(theta, [1, 2, 4, 4])
    grid.sum().backward()
    assert np.abs(theta.grad.numpy()).sum() > 0
    with pytest.raises(ValueError, match="batch"):
        F.affine_grid(theta, [3, 2, 4, 4])


def test_affine_grid_identity_with_grid_sample():
    """Identity theta + grid_sample reproduces the input."""
    rng = np.random.RandomState(3)
    x = pt.to_tensor(rng.randn(1, 2, 6, 6).astype(np.float32))
    theta = np.tile(np.array([[[1.0, 0, 0], [0, 1.0, 0]]], np.float32),
                    (1, 1, 1))
    grid = F.affine_grid(pt.to_tensor(theta), [1, 2, 6, 6],
                         align_corners=True)
    out = F.grid_sample(x, grid, align_corners=True)
    np.testing.assert_allclose(out.numpy(), x.numpy(), rtol=1e-4,
                               atol=1e-5)
