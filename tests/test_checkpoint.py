"""Checkpoint/resume + inference export.

Mirrors the reference's io tests (test/legacy_test/test_paddle_save_load.py,
test_jit_save_load.py): deterministic resume equality, state round-trips,
TranslatedLayer replay.
"""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import nn


def _make(seed=0):
    pt.seed(seed)
    m = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
    opt = pt.optimizer.AdamW(learning_rate=1e-2, parameters=m.parameters())
    sched = None
    return m, opt


def _step(m, opt, x, y):
    loss = ((m(x) - y) ** 2).mean()
    loss.backward()
    opt.step()
    opt.clear_grad()
    return float(loss)


def test_deterministic_resume(tmp_path):
    m, opt = _make()
    x = pt.randn([16, 8])
    y = pt.randn([16, 4])
    for _ in range(3):
        _step(m, opt, x, y)
    pt.save_state(str(tmp_path / "ck"), model=m, optimizer=opt, step=3)
    # branch A: continue directly
    a_losses = [_step(m, opt, x, y) for _ in range(3)]

    # branch B: fresh model+opt, restore, continue — must match exactly
    m2, opt2 = _make(seed=123)  # different init, overwritten by restore
    meta = pt.load_state(str(tmp_path / "ck"), model=m2, optimizer=opt2)
    assert meta["step"] == 3
    b_losses = [_step(m2, opt2, x, y) for _ in range(3)]
    np.testing.assert_allclose(a_losses, b_losses, rtol=1e-6)


def test_checkpoint_scaler_and_extra(tmp_path):
    m, opt = _make()
    scaler = pt.amp.GradScaler(init_loss_scaling=64.0)
    pt.save_state(str(tmp_path / "ck"), model=m, optimizer=opt,
                  scaler=scaler, step=7, extra={"epoch": 2})
    scaler2 = pt.amp.GradScaler(init_loss_scaling=1.0)
    m2, opt2 = _make(seed=9)
    meta = pt.load_state(str(tmp_path / "ck"), model=m2, optimizer=opt2,
                         scaler=scaler2)
    assert scaler2.get_loss_scaling() == 64.0
    assert meta["extra"]["epoch"] == 2


def test_rng_restored(tmp_path):
    m, opt = _make()
    pt.seed(42)
    pt.save_state(str(tmp_path / "ck"), model=m, optimizer=opt)
    r1 = pt.randn([4]).numpy()
    pt.seed(7)  # perturb the stream
    pt.load_state(str(tmp_path / "ck"), model=m, optimizer=opt)
    r2 = pt.randn([4]).numpy()
    np.testing.assert_allclose(r1, r2)


def test_lr_scheduler_in_checkpoint(tmp_path):
    pt.seed(0)
    m = nn.Linear(4, 4)
    sched = pt.optimizer.lr.StepDecay(learning_rate=0.1, step_size=2)
    opt = pt.optimizer.SGD(learning_rate=sched, parameters=m.parameters())
    for _ in range(5):
        sched.step()
    pt.save_state(str(tmp_path / "ck"), model=m, optimizer=opt)
    sched2 = pt.optimizer.lr.StepDecay(learning_rate=0.1, step_size=2)
    m2 = nn.Linear(4, 4)
    opt2 = pt.optimizer.SGD(learning_rate=sched2, parameters=m2.parameters())
    pt.load_state(str(tmp_path / "ck"), model=m2, optimizer=opt2)
    assert sched2.get_lr() == pytest.approx(sched.get_lr())


def test_inconsistent_checkpoint_detected(tmp_path):
    import json
    m, opt = _make()
    path = tmp_path / "ck"
    pt.save_state(str(path), model=m, optimizer=opt, step=1)
    # simulate a crash mid-overwrite: meta from a different save
    meta_file = path / "meta.json"
    meta = json.loads(meta_file.read_text())
    meta["commit_token"] = "00" * 16
    meta_file.write_text(json.dumps(meta))
    m2, opt2 = _make(seed=1)
    with pytest.raises(RuntimeError, match="inconsistent"):
        pt.load_state(str(path), model=m2, optimizer=opt2)


def test_jit_save_load_inference(tmp_path):
    pt.seed(0)
    m = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
    m.eval()
    path = str(tmp_path / "inf")
    pt.jit.save(m, path, input_spec=[pt.jit.InputSpec([2, 8])])
    x = pt.randn([2, 8])
    want = m(x).numpy()
    tl = pt.jit.load(path)
    got = tl(x).numpy()
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_jit_save_load_dynamic_batch(tmp_path):
    pt.seed(0)
    m = nn.Linear(8, 4)
    m.eval()
    path = str(tmp_path / "inf_dyn")
    pt.jit.save(m, path, input_spec=[pt.jit.InputSpec([None, 8])])
    tl = pt.jit.load(path)
    for bs in (1, 3, 17):
        x = pt.randn([bs, 8])
        np.testing.assert_allclose(tl(x).numpy(), m(x).numpy(),
                                   rtol=1e-5, atol=1e-6)


def test_jit_save_load_with_buffers(tmp_path):
    pt.seed(0)
    m = nn.Sequential(nn.Linear(8, 8), nn.BatchNorm1D(8))
    x = pt.randn([16, 8])
    m.train()
    m(x)  # populate running stats
    m.eval()
    path = str(tmp_path / "inf_bn")
    pt.jit.save(m, path, input_spec=[pt.jit.InputSpec([4, 8])])
    tl = pt.jit.load(path)
    xe = pt.randn([4, 8])
    np.testing.assert_allclose(tl(xe).numpy(), m(xe).numpy(),
                               rtol=1e-5, atol=1e-6)


def test_generic_pickle_save_load(tmp_path):
    m, _ = _make()
    p = str(tmp_path / "sd.pdparams")
    pt.save(m.state_dict(), p)
    sd = pt.load(p)
    m2, _ = _make(seed=5)
    m2.set_state_dict(sd)
    x = pt.randn([2, 8])
    np.testing.assert_allclose(m2(x).numpy(), m(x).numpy(), rtol=1e-6)
