"""Checkpoint/resume + inference export.

Mirrors the reference's io tests (test/legacy_test/test_paddle_save_load.py,
test_jit_save_load.py): deterministic resume equality, state round-trips,
TranslatedLayer replay.
"""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import nn



def _make(seed=0):
    pt.seed(seed)
    m = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
    opt = pt.optimizer.AdamW(learning_rate=1e-2, parameters=m.parameters())
    sched = None
    return m, opt


def _step(m, opt, x, y):
    loss = ((m(x) - y) ** 2).mean()
    loss.backward()
    opt.step()
    opt.clear_grad()
    return float(loss)


def test_deterministic_resume(tmp_path):
    m, opt = _make()
    x = pt.randn([16, 8])
    y = pt.randn([16, 4])
    for _ in range(3):
        _step(m, opt, x, y)
    pt.save_state(str(tmp_path / "ck"), model=m, optimizer=opt, step=3)
    # branch A: continue directly
    a_losses = [_step(m, opt, x, y) for _ in range(3)]

    # branch B: fresh model+opt, restore, continue — must match exactly
    m2, opt2 = _make(seed=123)  # different init, overwritten by restore
    meta = pt.load_state(str(tmp_path / "ck"), model=m2, optimizer=opt2)
    assert meta["step"] == 3
    b_losses = [_step(m2, opt2, x, y) for _ in range(3)]
    np.testing.assert_allclose(a_losses, b_losses, rtol=1e-6)


def test_checkpoint_scaler_and_extra(tmp_path):
    m, opt = _make()
    scaler = pt.amp.GradScaler(init_loss_scaling=64.0)
    pt.save_state(str(tmp_path / "ck"), model=m, optimizer=opt,
                  scaler=scaler, step=7, extra={"epoch": 2})
    scaler2 = pt.amp.GradScaler(init_loss_scaling=1.0)
    m2, opt2 = _make(seed=9)
    meta = pt.load_state(str(tmp_path / "ck"), model=m2, optimizer=opt2,
                         scaler=scaler2)
    assert scaler2.get_loss_scaling() == 64.0
    assert meta["extra"]["epoch"] == 2


def test_rng_restored(tmp_path):
    m, opt = _make()
    pt.seed(42)
    pt.save_state(str(tmp_path / "ck"), model=m, optimizer=opt)
    r1 = pt.randn([4]).numpy()
    pt.seed(7)  # perturb the stream
    pt.load_state(str(tmp_path / "ck"), model=m, optimizer=opt)
    r2 = pt.randn([4]).numpy()
    np.testing.assert_allclose(r1, r2)


def test_lr_scheduler_in_checkpoint(tmp_path):
    pt.seed(0)
    m = nn.Linear(4, 4)
    sched = pt.optimizer.lr.StepDecay(learning_rate=0.1, step_size=2)
    opt = pt.optimizer.SGD(learning_rate=sched, parameters=m.parameters())
    for _ in range(5):
        sched.step()
    pt.save_state(str(tmp_path / "ck"), model=m, optimizer=opt)
    sched2 = pt.optimizer.lr.StepDecay(learning_rate=0.1, step_size=2)
    m2 = nn.Linear(4, 4)
    opt2 = pt.optimizer.SGD(learning_rate=sched2, parameters=m2.parameters())
    pt.load_state(str(tmp_path / "ck"), model=m2, optimizer=opt2)
    assert sched2.get_lr() == pytest.approx(sched.get_lr())


def test_inconsistent_checkpoint_detected(tmp_path):
    import json
    m, opt = _make()
    path = tmp_path / "ck"
    pt.save_state(str(path), model=m, optimizer=opt, step=1)
    # simulate a crash mid-overwrite: meta from a different save
    meta_file = path / "meta.json"
    meta = json.loads(meta_file.read_text())
    meta["commit_token"] = "00" * 16
    meta_file.write_text(json.dumps(meta))
    m2, opt2 = _make(seed=1)
    with pytest.raises(RuntimeError, match="inconsistent"):
        pt.load_state(str(path), model=m2, optimizer=opt2)


def test_jit_save_load_inference(tmp_path):
    pt.seed(0)
    m = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
    m.eval()
    path = str(tmp_path / "inf")
    pt.jit.save(m, path, input_spec=[pt.jit.InputSpec([2, 8])])
    x = pt.randn([2, 8])
    want = m(x).numpy()
    tl = pt.jit.load(path)
    got = tl(x).numpy()
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_jit_save_load_dynamic_batch(tmp_path):
    pt.seed(0)
    m = nn.Linear(8, 4)
    m.eval()
    path = str(tmp_path / "inf_dyn")
    pt.jit.save(m, path, input_spec=[pt.jit.InputSpec([None, 8])])
    tl = pt.jit.load(path)
    for bs in (1, 3, 17):
        x = pt.randn([bs, 8])
        np.testing.assert_allclose(tl(x).numpy(), m(x).numpy(),
                                   rtol=1e-5, atol=1e-6)


def test_jit_save_load_with_buffers(tmp_path):
    pt.seed(0)
    m = nn.Sequential(nn.Linear(8, 8), nn.BatchNorm1D(8))
    x = pt.randn([16, 8])
    m.train()
    m(x)  # populate running stats
    m.eval()
    path = str(tmp_path / "inf_bn")
    pt.jit.save(m, path, input_spec=[pt.jit.InputSpec([4, 8])])
    tl = pt.jit.load(path)
    xe = pt.randn([4, 8])
    np.testing.assert_allclose(tl(xe).numpy(), m(xe).numpy(),
                               rtol=1e-5, atol=1e-6)


def test_generic_pickle_save_load(tmp_path):
    m, _ = _make()
    p = str(tmp_path / "sd.pdparams")
    pt.save(m.state_dict(), p)
    sd = pt.load(p)
    m2, _ = _make(seed=5)
    m2.set_state_dict(sd)
    x = pt.randn([2, 8])
    np.testing.assert_allclose(m2(x).numpy(), m(x).numpy(), rtol=1e-6)


@pytest.mark.needs_partial_manual
def test_fleet_engine_resume_matches_uninterrupted(tmp_path):
    """Checkpoint/resume THROUGH the fleet engine (pp + dp + Adam state):
    save after 2 steps, rebuild everything, load, continue — losses must
    match an uninterrupted 4-step run exactly."""
    from paddle_tpu.distributed import fleet, mesh as mesh_mod
    from paddle_tpu.text import GPTConfig, GPTForCausalLM, gpt_loss_fn
    prev = dict(mesh_mod._state)

    def build():
        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 1,
                                   "pp_degree": 2, "accumulate_steps": 2}
        fleet.init(is_collective=True, strategy=strategy)
        pt.seed(7)
        cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=4,
                        num_heads=4, max_position_embeddings=32,
                        hidden_dropout=0.0, attention_dropout=0.0,
                        tensor_parallel=False)
        m = GPTForCausalLM(cfg)
        opt = pt.optimizer.Adam(learning_rate=0.02,
                                parameters=m.parameters())
        return m, opt, fleet.build_train_step(m, gpt_loss_fn, opt)

    try:
        pt.seed(3)
        ids = pt.randint(0, 64, [4, 16])
        labels = pt.randint(0, 64, [4, 16])

        # uninterrupted 4-step run
        m1, _, step1 = build()
        ref_losses = [float(step1(ids, labels)) for _ in range(4)]

        # interrupted: 2 steps -> save -> rebuild -> load -> 2 more steps
        m2, _, step2 = build()
        for _ in range(2):
            step2(ids, labels)
        pt.save_state(str(tmp_path / "fleet_ck"), model=m2, optimizer=step2)

        m3, _, step3 = build()
        pt.load_state(str(tmp_path / "fleet_ck"), model=m3, optimizer=step3)
        resumed = [float(step3(ids, labels)) for _ in range(2)]
        np.testing.assert_allclose(resumed, ref_losses[2:], rtol=1e-5)
    finally:
        mesh_mod._state.update(prev)


@pytest.mark.needs_partial_manual
def test_fleet_resume_topology_guards(tmp_path):
    """Wrong-topology or eager-format checkpoints must fail loudly, and a
    save-after-load-before-step round-trip must not drop the moments."""
    from paddle_tpu.distributed import fleet, mesh as mesh_mod
    from paddle_tpu.text import GPTConfig, GPTForCausalLM, gpt_loss_fn
    prev = dict(mesh_mod._state)

    def build(vpp):
        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 1,
                                   "pp_degree": 2, "accumulate_steps": 2,
                                   "virtual_pp_degree": vpp}
        fleet.init(is_collective=True, strategy=strategy)
        pt.seed(7)
        cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=4,
                        num_heads=4, max_position_embeddings=32,
                        hidden_dropout=0.0, attention_dropout=0.0,
                        tensor_parallel=False)
        m = GPTForCausalLM(cfg)
        opt = pt.optimizer.Adam(learning_rate=0.02,
                                parameters=m.parameters())
        return m, fleet.build_train_step(m, gpt_loss_fn, opt)

    try:
        pt.seed(3)
        ids = pt.randint(0, 64, [4, 16])
        labels = pt.randint(0, 64, [4, 16])
        m1, s1 = build(vpp=2)
        s1(ids, labels)
        pt.save_state(str(tmp_path / "vpp2"), model=m1, optimizer=s1)

        # vpp mismatch -> loud error (stacked rows would be layer-permuted)
        m2, s2 = build(vpp=1)
        with pytest.raises(ValueError, match="topology"):
            pt.load_state(str(tmp_path / "vpp2"), model=m2, optimizer=s2)

        # eager-format checkpoint into a pp engine -> loud error
        pt.seed(7)
        from paddle_tpu.text import GPTConfig as _C
        cfg = _C(vocab_size=64, hidden_size=32, num_layers=4, num_heads=4,
                 max_position_embeddings=32, hidden_dropout=0.0,
                 attention_dropout=0.0, tensor_parallel=False)
        me = GPTForCausalLM(cfg)
        oe = pt.optimizer.Adam(learning_rate=0.02, parameters=me.parameters())
        gpt_loss_fn(me, ids, labels).backward()
        oe.step(); oe.clear_grad()
        pt.save_state(str(tmp_path / "eager"), model=me, optimizer=oe)
        m3, s3 = build(vpp=2)
        with pytest.raises(ValueError, match="non-pp"):
            pt.load_state(str(tmp_path / "eager"), model=m3, optimizer=s3)

        # save-after-load-before-step keeps the loaded moments
        m4, s4 = build(vpp=2)
        pt.load_state(str(tmp_path / "vpp2"), model=m4, optimizer=s4)
        sd = s4.state_dict()
        assert any("__stacked__" in k for k in sd)
        pt.save_state(str(tmp_path / "resaved"), model=m4, optimizer=s4)
        m5, s5 = build(vpp=2)
        pt.load_state(str(tmp_path / "resaved"), model=m5, optimizer=s5)
        l5 = float(s5(ids, labels))
        m6, s6 = build(vpp=2)
        pt.load_state(str(tmp_path / "vpp2"), model=m6, optimizer=s6)
        l6 = float(s6(ids, labels))
        np.testing.assert_allclose(l5, l6, rtol=1e-6)
    finally:
        mesh_mod._state.update(prev)


def test_eager_optimizer_rejects_stacked_checkpoint():
    m = pt.nn.Linear(4, 4)
    opt = pt.optimizer.Adam(learning_rate=0.1, parameters=m.parameters())
    with pytest.raises(ValueError, match="fleet"):
        opt.set_state_dict({"weight/__stacked__/moment1": pt.zeros([2, 4])})
